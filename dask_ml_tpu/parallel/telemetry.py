"""Unified telemetry: hierarchical spans, a metrics registry, trace export.

The reference got its runtime observability for free from dask's
distributed scheduler dashboards (SURVEY §0: dask-ml ships no runtime of
its own); this JAX rebuild has no such dashboard, and six PRs of substrate
work left telemetry scattered over five incompatible ad-hoc surfaces —
``utils/_log.py::profile_phase`` wall times, ``parallel/shapes.py::
compile_stats()``, the :class:`~dask_ml_tpu.parallel.stream.HostBlockSource`
wire/logical byte counters, ``RetryPolicy.stats()`` / the search's
``retry_stats_``, and KMeans' ``lloyd_pruning_``. This module is the one
subsystem they all report through (docs/observability.md):

- **Hierarchical spans** — :func:`span` is a context manager recording wall
  time, optional device-sync time (``sp.sync(tree)`` measures the
  ``block_until_ready`` wait), and parent/child structure into a bounded
  ring-buffer recorder (thread-local nesting; the ring is process-wide).
  Spans still emit ``jax.profiler.TraceAnnotation`` and honor the existing
  ``DASK_ML_TPU_PROFILE_DIR`` outermost-capture contract, so externally
  captured xprof traces keep seeing the same phase names —
  ``utils/_log.py::profile_phase`` is now a thin compatibility wrapper over
  ``span(name, logger=...)``.
- **Metrics registry** — thread-safe named counters / gauges / histograms
  with label support (:func:`counter` / :func:`gauge` / :func:`histogram`),
  into which every pre-existing ad-hoc counter is mirrored at its
  increment site: stream wire/logical bytes and blocks, the prefetch
  queue-depth gauge sampled at each ``take()`` (the direct precursor to
  serving queue-depth, ROADMAP item 1), retry/backoff/giveup counters from
  :mod:`~dask_ml_tpu.parallel.faults`, search-cell timeouts, compile events
  and shape-bucket hits from :mod:`~dask_ml_tpu.parallel.shapes`, and
  Lloyd pruning fractions from ``models/kmeans.py``.
- **Export** — :func:`telemetry_report` returns one unified nested dict
  (JSON-round-trippable; :func:`render_report` is the text view wired into
  the search's ``shared_fit_report()``), and :func:`export_chrome_trace`
  writes Chrome trace-event JSON loadable in Perfetto /
  ``chrome://tracing``.

Everything is behind the thread-local ``telemetry`` config knob
(:mod:`dask_ml_tpu.config`): with the knob off (the default) the
instrumented call sites take a measured near-no-op path — a disabled
:func:`span` yields a shared null span without touching the recorder or
``jax.profiler``, and a disabled metric helper returns a shared null metric
whose ``inc``/``set``/``observe`` are empty methods. ``bench.py
--telemetry`` gates that the disabled path costs < 1 % of fit wall time
(TELEMETRY_r01.json).

Mirror semantics: metric mirrors are exact WITHIN an enabled scope — reset
with :func:`reset_telemetry`, enable via ``config_context(telemetry=True)``,
run the workload, and every mirrored counter equals its legacy surface
(``tests/test_telemetry.py`` pins this under the PR-3 ``FaultInjector``).
Compile numbers appear twice with different scopes, by design: the
report's ``compile`` section pulls
:func:`~dask_ml_tpu.parallel.shapes.compile_stats` live (process-lifetime,
the legacy surface itself), while the ``compile.*`` registry counters
count only events that fired inside an enabled scope — warm-up compiles
before ``config_context(telemetry=True)`` (or a ``reset_telemetry``, which
clears the registry but deliberately not ``compile_stats``) show up in the
former and not the latter.
"""

from __future__ import annotations

import contextlib
import json
import math
import os
import threading
import time
from collections import deque
from typing import Any, Optional

__all__ = [
    "span",
    "Span",
    "enabled",
    "metrics",
    "counter",
    "gauge",
    "histogram",
    "spans",
    "span_summary",
    "reset_telemetry",
    "telemetry_report",
    "render_report",
    "export_chrome_trace",
    "MetricsRegistry",
]

PROFILE_DIR_ENV = "DASK_ML_TPU_PROFILE_DIR"

#: process trace epoch — span timestamps (and the Chrome trace ``ts`` axis)
#: are seconds since this module was imported
_T0 = time.perf_counter()

_DEFAULT_RING_CAPACITY = 8192


_get_one = None  # bound on first use (config imports nothing from here,
# but binding lazily keeps module import order unconstrained)


def enabled() -> bool:
    """Whether telemetry recording is on for THIS thread (the ``telemetry``
    config knob: ``set_config(telemetry=True)`` process-wide,
    ``config_context(telemetry=True)`` scoped)."""
    global _get_one
    if _get_one is None:
        from dask_ml_tpu.config import _get_one as _g

        _get_one = _g
    return bool(_get_one("telemetry"))


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------


class _NullMetric:
    """Shared no-op metric returned by the module-level helpers when the
    knob is off — the disabled path allocates nothing and touches no lock."""

    __slots__ = ()

    def inc(self, v=1) -> None:
        pass

    def set(self, v) -> None:
        pass

    def observe(self, v) -> None:
        pass


_NULL_METRIC = _NullMetric()


class Counter:
    """Monotonic-by-convention named counter (mirrors may subtract when the
    legacy surface they shadow rolls back, e.g. ``discard_inflight``)."""

    __slots__ = ("_lock", "value")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.value = 0

    def inc(self, v=1) -> None:
        with self._lock:
            self.value += v


class Gauge:
    """Last-value gauge that also tracks min/max/sample count — enough to
    bound a sampled quantity (queue depth) without storing the series."""

    __slots__ = ("_lock", "last", "min", "max", "n_samples")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.last = None
        self.min = None
        self.max = None
        self.n_samples = 0

    def set(self, v) -> None:
        v = float(v)
        with self._lock:
            self.last = v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            self.n_samples += 1


#: raw-sample retention bound per histogram: percentiles are computed over
#: the most recent this-many observations (a sliding window — for serving
#: latency that is exactly the "recent traffic" view wanted; below the cap
#: the window is ALL observations, which is what the numpy-percentile pin
#: in tests/test_telemetry.py relies on)
HISTOGRAM_SAMPLE_CAP = 8192


class Histogram:
    """Count/sum/min/max plus power-of-two buckets (``le_2^e`` holds
    observations in ``(2^(e-1), 2^e]``; nonpositive values land in ``0``),
    plus a bounded window of raw samples (:data:`HISTOGRAM_SAMPLE_CAP` most
    recent) from which :meth:`percentiles` reads p50/p99-style summary
    stats — bounded memory however many observations arrive."""

    __slots__ = ("_lock", "count", "total", "min", "max", "buckets",
                 "samples")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.count = 0
        self.total = 0.0
        self.min = None
        self.max = None
        self.buckets: dict = {}
        self.samples: deque = deque(maxlen=HISTOGRAM_SAMPLE_CAP)

    @staticmethod
    def bucket_of(v: float) -> str:
        if v <= 0:
            return "0"
        return f"le_2^{int(math.ceil(math.log2(v)))}"

    def observe(self, v) -> None:
        v = float(v)
        b = self.bucket_of(v)
        with self._lock:
            self.count += 1
            self.total += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            self.buckets[b] = self.buckets.get(b, 0) + 1
            self.samples.append(v)

    def percentiles(self, q=(50, 90, 99)) -> dict:
        """``{"p50": ..., "p90": ..., "p99": ...}`` over the retained
        sample window, with numpy's default linear interpolation — pinned
        equal to ``np.percentile(samples, q)`` while the observation count
        stays under :data:`HISTOGRAM_SAMPLE_CAP` (beyond it the window
        slides to the most recent cap-many samples). Empty histogram →
        all ``None``."""
        # copy under the (registry-wide) lock, sort OUTSIDE it: an 8k-
        # sample sort must not stall concurrent metric writers on the
        # dispatch hot path
        with self._lock:
            data = list(self.samples)
        data.sort()
        out: dict = {}
        for qq in q:
            key = f"p{qq:g}"
            if not data:
                out[key] = None
                continue
            pos = (len(data) - 1) * (float(qq) / 100.0)
            lo = math.floor(pos)
            hi = math.ceil(pos)
            out[key] = data[lo] + (data[hi] - data[lo]) * (pos - lo)
        return out


class MetricsRegistry:
    """Thread-safe named counters/gauges/histograms with label support.

    A metric's identity is ``(name, sorted labels)``; the snapshot renders
    labeled metrics Prometheus-style (``name{k=v,...}``). One process-wide
    instance (:func:`metrics`) backs the module helpers; tests may
    construct private registries.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict = {}
        self._gauges: dict = {}
        self._histograms: dict = {}

    @staticmethod
    def _key(name: str, labels: dict) -> tuple:
        return (str(name),
                tuple(sorted((str(k), str(v)) for k, v in labels.items())))

    def _get(self, table: dict, cls, name: str, labels: dict):
        key = self._key(name, labels)
        with self._lock:
            m = table.get(key)
            if m is None:
                m = table[key] = cls(self._lock)
        return m

    def counter(self, name: str, **labels) -> Counter:
        return self._get(self._counters, Counter, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get(self._gauges, Gauge, name, labels)

    def histogram(self, name: str, **labels) -> Histogram:
        return self._get(self._histograms, Histogram, name, labels)

    @staticmethod
    def _render_key(key: tuple) -> str:
        name, labels = key
        if not labels:
            return name
        return name + "{" + ",".join(f"{k}={v}" for k, v in labels) + "}"

    def snapshot(self) -> dict:
        """Plain-dict view of every metric — JSON-serializable, keys are
        the rendered ``name{labels}`` strings."""
        with self._lock:
            counters = {self._render_key(k): c.value
                        for k, c in sorted(self._counters.items())}
            gauges = {
                self._render_key(k): {
                    "last": g.last, "min": g.min, "max": g.max,
                    "n_samples": g.n_samples,
                }
                for k, g in sorted(self._gauges.items())
            }
            hist_items = sorted(self._histograms.items())
        # percentiles take the shared lock per histogram; computed OUTSIDE
        # the snapshot lock hold so a large sample window never stalls
        # other metric writers behind a sort
        histograms = {}
        for k, h in hist_items:
            with self._lock:
                rec = {
                    "count": h.count,
                    "sum": h.total,
                    "min": h.min,
                    "max": h.max,
                    "mean": (h.total / h.count) if h.count else None,
                    "buckets": dict(h.buckets),
                    "n_samples_retained": len(h.samples),
                }
            rec.update(h.percentiles())
            histograms[self._render_key(k)] = rec
        return {"counters": counters, "gauges": gauges,
                "histograms": histograms}

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()


_registry = MetricsRegistry()


def metrics() -> MetricsRegistry:
    """The process-wide registry (bypasses the enabled check — use for
    multi-metric hot sites already guarded by one :func:`enabled` call, and
    for reading)."""
    return _registry


def counter(name: str, **labels):
    """Named counter, or the shared null metric when telemetry is off."""
    if not enabled():
        return _NULL_METRIC
    return _registry.counter(name, **labels)


def gauge(name: str, **labels):
    """Named gauge, or the shared null metric when telemetry is off."""
    if not enabled():
        return _NULL_METRIC
    return _registry.gauge(name, **labels)


def histogram(name: str, **labels):
    """Named histogram, or the shared null metric when telemetry is off."""
    if not enabled():
        return _NULL_METRIC
    return _registry.histogram(name, **labels)


# ---------------------------------------------------------------------------
# hierarchical spans
# ---------------------------------------------------------------------------


class Span:
    """One live span: mutate ``attrs`` via :meth:`set`, measure device-sync
    waits via :meth:`sync`. Finished spans land in the ring buffer as plain
    dicts (:func:`spans`)."""

    __slots__ = ("name", "attrs", "sid", "parent_id", "depth", "tid",
                 "thread_name", "ts", "dur", "sync_seconds")

    def __init__(self, name, attrs, sid, parent_id, depth, tid, thread_name):
        self.name = name
        self.attrs = attrs
        self.sid = sid
        self.parent_id = parent_id
        self.depth = depth
        self.tid = tid
        self.thread_name = thread_name
        self.ts = 0.0
        self.dur = 0.0
        self.sync_seconds = 0.0

    def set(self, **attrs) -> None:
        self.attrs.update(attrs)

    def sync(self, tree):
        """``jax.block_until_ready(tree)`` with the wait time recorded as
        this span's ``sync_seconds`` — how much of the span was the host
        waiting on the device, vs dispatching. Returns ``tree``.

        MEASUREMENT ONLY: on a disabled span this is a pass-through no-op
        (no barrier), so call sites must never rely on it for
        correctness-critical synchronization."""
        import jax

        t0 = time.perf_counter()
        jax.block_until_ready(tree)
        self.sync_seconds += time.perf_counter() - t0
        return tree


class _NullSpan:
    """Shared span stand-in on the disabled path: ``set`` and ``sync`` are
    no-ops (``sync`` does NOT block — see :meth:`Span.sync`)."""

    __slots__ = ()

    def set(self, **attrs) -> None:
        pass

    def sync(self, tree):
        return tree


_NULL_SPAN = _NullSpan()


class _NullSpanCtx:
    """Shared context manager for the disabled no-``logger`` path: ``with
    span(...)`` then costs one knob read plus this singleton's trivial
    enter/exit — no generator frame, no environ read, no allocation."""

    __slots__ = ()

    def __enter__(self):
        return _NULL_SPAN

    def __exit__(self, *exc):
        return False


_NULL_SPAN_CTX = _NullSpanCtx()

_lock = threading.Lock()
_ring: deque = deque(maxlen=_DEFAULT_RING_CAPACITY)
_dropped = 0
_next_id = 0
_tls = threading.local()


def _stack() -> list:
    s = getattr(_tls, "stack", None)
    if s is None:
        s = _tls.stack = []
    return s


def _alloc_id() -> int:
    global _next_id
    with _lock:
        _next_id += 1
        return _next_id


def _record(sp: Span) -> None:
    global _dropped
    rec = {
        "name": sp.name,
        "ts": sp.ts,
        "dur": sp.dur,
        "sync_seconds": sp.sync_seconds,
        "tid": sp.tid,
        "thread": sp.thread_name,
        "id": sp.sid,
        "parent": sp.parent_id,
        "depth": sp.depth,
        "attrs": dict(sp.attrs),
    }
    with _lock:
        if _ring.maxlen is not None and len(_ring) == _ring.maxlen:
            _dropped += 1
        _ring.append(rec)


def span(name: str, *, logger=None, **attrs):
    """Hierarchical telemetry span around a fit phase / block / cell.

    With the ``telemetry`` knob on, records wall time, thread-local
    parent/child structure, and any ``**attrs`` into the bounded ring
    buffer, emitting a ``jax.profiler.TraceAnnotation`` so externally
    captured traces see the same name. With the knob off (and no
    ``logger``) this is a measured near-no-op: one config read and a
    shared null context manager, nothing recorded.

    ``logger`` opts into the legacy ``profile_phase`` contract regardless
    of the knob: the phase ALWAYS gets a ``TraceAnnotation`` plus a DEBUG
    wall-time line, and when ``DASK_ML_TPU_PROFILE_DIR`` is set the
    outermost such span per thread captures a full ``jax.profiler.trace``
    into that directory (logged at INFO) — byte-for-byte the behavior
    ``utils/_log.py::profile_phase`` always had, which is now a thin
    wrapper over this. The env var is consulted only for ``logger``
    spans: capture sites are exactly the (pre-telemetry) profile_phase
    sites, and plain spans never pay the environ read.

    The yielded :class:`Span` supports ``sp.set(key=value)`` for late
    attributes and ``sp.sync(tree)`` to attribute device-sync wait time.
    """
    if logger is None and not enabled():
        return _NULL_SPAN_CTX
    return _span_impl(name, logger, attrs)


@contextlib.contextmanager
def _span_impl(name: str, logger, attrs: dict):
    rec = enabled()
    trace_dir = (os.environ.get(PROFILE_DIR_ENV) if logger is not None
                 else None)
    import jax.profiler

    own_trace = bool(trace_dir) and not getattr(_tls, "trace_active", False)
    if own_trace:
        _tls.trace_active = True
        jax.profiler.start_trace(trace_dir)
    sp = _NULL_SPAN
    stack = None
    if rec:
        stack = _stack()
        parent = stack[-1] if stack else None
        th = threading.current_thread()
        sp = Span(
            name=str(name), attrs=dict(attrs), sid=_alloc_id(),
            parent_id=(parent.sid if parent is not None else None),
            depth=(parent.depth + 1 if parent is not None else 0),
            tid=th.ident, thread_name=th.name,
        )
        stack.append(sp)
    t0 = time.perf_counter()
    try:
        with jax.profiler.TraceAnnotation(str(name)):
            yield sp
    finally:
        dt = time.perf_counter() - t0
        if rec:
            if stack and stack[-1] is sp:
                stack.pop()
            else:  # a leaked inner generator: drop by identity, not order
                try:
                    stack.remove(sp)
                except ValueError:
                    pass
            sp.ts = t0 - _T0
            sp.dur = dt
            _record(sp)
        if own_trace:
            _tls.trace_active = False
            jax.profiler.stop_trace()
            if logger is not None:
                logger.info("phase %s: %.3fs (trace -> %s)", name, dt,
                            trace_dir)
        elif logger is not None:
            logger.debug("phase %s: %.3fs", name, dt)


def spans() -> list:
    """Finished-span records (oldest first), each a plain dict with
    ``name/ts/dur/sync_seconds/tid/thread/id/parent/depth/attrs``."""
    with _lock:
        return list(_ring)


def span_summary() -> dict:
    """Per-name aggregate over the recorded spans: count, total/max wall
    seconds, total device-sync seconds."""
    out: dict = {}
    for r in spans():
        s = out.setdefault(r["name"], {
            "count": 0, "total_seconds": 0.0, "max_seconds": 0.0,
            "sync_seconds": 0.0,
        })
        s["count"] += 1
        s["total_seconds"] += r["dur"]
        s["max_seconds"] = max(s["max_seconds"], r["dur"])
        s["sync_seconds"] += r["sync_seconds"]
    for s in out.values():
        for k in ("total_seconds", "max_seconds", "sync_seconds"):
            s[k] = round(s[k], 6)
    return out


def reset_telemetry(ring_capacity: Optional[int] = None) -> None:
    """Clear the span ring buffer and the metrics registry (compile stats
    are :func:`~dask_ml_tpu.parallel.shapes.reset_compile_stats`'s to
    reset — they pre-date this module and other consumers read them).
    ``ring_capacity`` optionally resizes the ring."""
    global _ring, _dropped
    with _lock:
        cap = _ring.maxlen if ring_capacity is None else int(ring_capacity)
        if cap is not None and cap < 1:
            raise ValueError(f"ring_capacity must be >= 1, got {cap}")
        _ring = deque(maxlen=cap)
        _dropped = 0
    _registry.reset()


# ---------------------------------------------------------------------------
# export
# ---------------------------------------------------------------------------


def telemetry_report() -> dict:
    """The one unified observability dict: span aggregates, every registry
    metric, and the live compile stats (pulled from
    :func:`~dask_ml_tpu.parallel.shapes.compile_stats` — the report IS that
    legacy surface, so the two can never disagree). JSON-round-trippable:
    ``json.loads(json.dumps(telemetry_report()))`` reproduces it exactly.
    """
    from dask_ml_tpu.parallel.shapes import compile_stats

    compile_ = dict(compile_stats())
    # json object keys are strings; stringify the bucket sizes here so the
    # report round-trips through json unchanged (nnz_buckets are the
    # sparse tier's ELL-width buckets — docs/sparse.md)
    compile_["shape_buckets"] = {
        str(k): v for k, v in compile_["shape_buckets"].items()}
    compile_["nnz_buckets"] = {
        str(k): v for k, v in compile_.get("nnz_buckets", {}).items()}
    compile_["col_buckets"] = {
        str(k): v for k, v in compile_.get("col_buckets", {}).items()}
    with _lock:
        n_recorded, n_dropped, cap = len(_ring), _dropped, _ring.maxlen
    return {
        "enabled": enabled(),
        "spans": {
            "by_name": span_summary(),
            "n_recorded": n_recorded,
            "n_dropped": n_dropped,
            "ring_capacity": cap,
        },
        "metrics": _registry.snapshot(),
        "compile": compile_,
    }


def render_report(max_rows: int = 12) -> str:
    """Text rendering of :func:`telemetry_report` (the view
    ``shared_fit_report()`` appends when telemetry is enabled)."""
    rep = telemetry_report()
    sp = rep["spans"]
    lines = [
        f"telemetry: {sp['n_recorded']} spans recorded"
        + (f" ({sp['n_dropped']} dropped)" if sp["n_dropped"] else ""),
    ]
    by_name = sorted(sp["by_name"].items(),
                     key=lambda kv: -kv[1]["total_seconds"])
    if by_name:
        lines.append(f"  {'total_s':>9}  {'count':>6}  {'sync_s':>8}  span")
        for name, s in by_name[:max_rows]:
            lines.append(f"  {s['total_seconds']:>9.3f}  {s['count']:>6}"
                         f"  {s['sync_seconds']:>8.3f}  {name}")
    m = rep["metrics"]
    for name, v in list(m["counters"].items())[:max_rows]:
        lines.append(f"  counter {name} = {v}")
    for name, g in list(m["gauges"].items())[:max_rows]:
        lines.append(f"  gauge {name}: last={g['last']} min={g['min']} "
                     f"max={g['max']} n={g['n_samples']}")
    for name, h in list(m["histograms"].items())[:max_rows]:
        mean = "n/a" if h["mean"] is None else f"{h['mean']:.4g}"
        pcts = "".join(
            f" {k}={h[k]:.4g}" for k in ("p50", "p90", "p99")
            if h.get(k) is not None)
        lines.append(f"  histogram {name}: count={h['count']} mean={mean} "
                     f"min={h['min']} max={h['max']}{pcts}")
    c = rep["compile"]
    lines.append(f"  compile: {c['n_compiles']} compiles "
                 f"({c['compile_seconds']:.2f}s), {c['n_traces']} traces, "
                 f"{len(c['shape_buckets'])} shape buckets")
    return "\n".join(lines)


def _json_safe(v: Any):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


def export_chrome_trace(path: str) -> str:
    """Write the recorded spans as Chrome trace-event JSON (the
    ``traceEvents`` array format), loadable in Perfetto
    (https://ui.perfetto.dev) or ``chrome://tracing``.

    Every finished span becomes one complete (``"ph": "X"``) event —
    nesting on a track follows ts/dur containment, which matches the
    recorded parent/child structure because spans on one thread strictly
    nest. ``args`` carries the span attrs, the span/parent ids, and the
    measured device-sync seconds. Returns ``path``.
    """
    recs = spans()
    pid = os.getpid()
    events: list = [{
        "ph": "M", "pid": pid, "tid": 0, "name": "process_name",
        "args": {"name": "dask_ml_tpu"},
    }]
    seen_tids: set = set()
    for r in recs:
        if r["tid"] not in seen_tids:
            seen_tids.add(r["tid"])
            events.append({
                "ph": "M", "pid": pid, "tid": r["tid"],
                "name": "thread_name", "args": {"name": r["thread"]},
            })
        args = {k: _json_safe(v) for k, v in r["attrs"].items()}
        args["span_id"] = r["id"]
        if r["parent"] is not None:
            args["parent_span_id"] = r["parent"]
        if r["sync_seconds"]:
            args["sync_seconds"] = round(r["sync_seconds"], 6)
        events.append({
            "name": r["name"],
            "cat": "dask_ml_tpu",
            "ph": "X",
            "pid": pid,
            "tid": r["tid"],
            "ts": round(r["ts"] * 1e6, 3),
            "dur": round(r["dur"] * 1e6, 3),
            "args": args,
        })
    path = os.fspath(path)
    with open(path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return path
