"""Meta-estimators: ParallelPostFit (parallel inference) and Incremental
(sequential blockwise partial_fit).

The reference bridges sklearn estimators and dask collections
(reference: wrappers.py:124-272 ``ParallelPostFit``, :275-395 ``Incremental``,
_partial.py:104-182 the sequential chain builder). The TPU-native rebuild
keeps the same two capabilities with a dual execution path:

- **jax-native estimators** (anything from this package): predict/transform
  already run as one SPMD program over the sharded input — the mesh *is* the
  ``map_blocks`` — so the wrapper simply delegates. For incremental training,
  :func:`incremental_scan` fuses the whole block chain into a single
  ``lax.scan`` (model-state carry updated in place by XLA): the reference's
  deliberately serial task chain (its docstring: "without any parallelism",
  _partial.py:222-224) becomes *faster serial* — one compiled program, zero
  per-block host round-trips.
- **foreign (sklearn-style) estimators**: host compute. ParallelPostFit
  splits the input into row blocks and fans them over a thread pool (sklearn
  kernels release the GIL; this is the moral equivalent of the reference's
  threaded scheduler executing one task per block), concatenating results.
  Incremental feeds blocks to ``partial_fit`` sequentially, exactly like the
  reference's linear task chain.

Both wrappers copy learned ``*_`` attributes onto themselves (reference:
wrappers.py:144-146 via _utils.copy_learned_attributes) and compose with
:class:`dask_ml_tpu.model_selection.GridSearchCV` through the standard
``estimator__<param>`` nesting.
"""

from __future__ import annotations

import logging
import threading
import weakref
from concurrent.futures import ThreadPoolExecutor
from timeit import default_timer as tic

import jax
import numpy as np
import sklearn.base
import sklearn.metrics
from sklearn.base import BaseEstimator, MetaEstimatorMixin
from sklearn.utils.validation import check_is_fitted

import scipy.sparse as sp

from dask_ml_tpu.metrics.scorer import check_scoring, get_scorer
from dask_ml_tpu.utils._utils import copy_learned_attributes

logger = logging.getLogger(__name__)

# Block size for host-side blockwise inference/training over foreign
# estimators — the analogue of the reference's "chunks" which it inherits
# from the input dask array (reference: utils.py:204-214 defaults to one
# block per core, >= 100 rows).
DEFAULT_BLOCK_SIZE = 100_000


def _is_jax_native(estimator) -> bool:
    """Heuristic for "this estimator already runs sharded on the mesh":
    anything defined in this package stages its own inputs."""
    mod = type(estimator).__module__ or ""
    return mod.startswith("dask_ml_tpu")


def _block_slices(n: int, block_size: int):
    for start in range(0, n, block_size):
        yield slice(start, min(start + block_size, n))


def _as_rowsliceable(X):
    """Row-sliceable view of X without densifying sparse matrices."""
    if sp.issparse(X):
        return X.tocsr()
    return np.asarray(X)


def _concat_rows(parts):
    if parts and sp.issparse(parts[0]):
        return sp.vstack(parts)
    return np.concatenate(parts, axis=0)


# Fit kwargs that are always per-row (sliced per block) vs. always metadata
# (never sliced, even if their length happens to equal n).
_ROW_ALIGNED_KWARGS = {"sample_weight"}
_NEVER_SLICED_KWARGS = {"classes"}


def _slice_kwargs(kwargs, s, n):
    """Slice per-row fit kwargs to match a block.

    ``sample_weight`` is sliced in any sequence form (sklearn accepts lists);
    ``classes`` is never sliced; other kwargs are sliced only when they are
    row-aligned ndarrays (length n)."""
    out = {}
    for k, v in kwargs.items():
        if k in _NEVER_SLICED_KWARGS:
            out[k] = v
        elif k in _ROW_ALIGNED_KWARGS and v is not None:
            out[k] = np.asarray(v)[s]
        elif isinstance(v, np.ndarray) and v.ndim >= 1 and len(v) == n:
            out[k] = v[s]
        else:
            out[k] = v
    return out


class ParallelPostFit(BaseEstimator, MetaEstimatorMixin):
    """Meta-estimator for parallel predict/transform after a plain fit
    (reference: wrappers.py:52-272).

    Parameters
    ----------
    estimator : Estimator
        The underlying estimator fit on small(ish) data.
    scoring : str or callable, optional
        Scorer used by :meth:`score`; default = estimator's own ``score``.
    block_size : int
        Rows per block for host-side blockwise inference over foreign
        estimators. jax-native estimators ignore it (the mesh shards
        instead).
    serving : ServingLoop, optional
        A started :class:`~dask_ml_tpu.parallel.serving.ServingLoop`:
        ``predict``/``predict_proba``/``transform`` become thin clients of
        the loop — the estimator is registered (idempotently, by identity)
        in the loop's :class:`~dask_ml_tpu.parallel.serving.ModelRegistry`
        on first use, requests above the loop's per-request row cap are
        chunked and their futures gathered, and results are bit-identical
        to the direct path (docs/serving.md). Sparse inputs and methods
        the loop does not serve fall back to the direct path. A refit
        through :meth:`fit` invalidates the loop's registration so stale
        fitted state is never served.
    serving_model : str, optional
        Explicit registry name (default: derived from the estimator).
    """

    def __init__(self, estimator=None, scoring=None,
                 block_size: int = DEFAULT_BLOCK_SIZE,
                 serving=None, serving_model=None):
        self.estimator = estimator
        self.scoring = scoring
        self.block_size = block_size
        self.serving = serving
        self.serving_model = serving_model

    @property
    def _postfit_estimator(self):
        return self.estimator

    def fit(self, X, y=None, **kwargs):
        """Fit the underlying estimator as-is (reference: wrappers.py:124-146)."""
        start = tic()
        logger.info("Starting fit")
        if self.serving is not None:
            # the runners closed over the PREVIOUS fitted state; drop them
            # before it mutates so a racing submit can never serve a
            # half-updated model
            self.serving.registry.invalidate(self.estimator)
        try:
            result = self.estimator.fit(X, y, **kwargs)
        finally:
            if self.serving is not None:
                # a predict racing this fit may have RE-registered the
                # estimator mid-mutation; drop that snapshot too so the
                # next request stages the final fitted state
                self.serving.registry.invalidate(self.estimator)
        logger.info("Finished fit, %0.2f", tic() - start)
        copy_learned_attributes(result, self)
        return self

    # -- blockwise dispatch ------------------------------------------------

    def _check_method(self, method):
        """AttributeError passthrough (reference: wrappers.py:260-272)."""
        estimator = self._postfit_estimator
        if not hasattr(estimator, method):
            raise AttributeError(
                f"The wrapped estimator '{estimator}' does not have a "
                f"'{method}' method."
            )
        return getattr(estimator, method)

    def _serving_name(self):
        est = self._postfit_estimator
        return self.serving.registry.ensure(est, name=self.serving_model)

    def _serving_call(self, method, X):
        """Route one logical request through the serving loop: chunk to
        the loop's per-request cap, submit every chunk (they coalesce
        with concurrent traffic loop-side), gather in order. One
        ``serving.request`` span per logical request."""
        from dask_ml_tpu.parallel import telemetry

        loop = self.serving
        name = self._serving_name()
        X = np.asarray(X)
        n = X.shape[0]
        with telemetry.span("serving.request", model=name, method=method,
                            rows=n):
            cap = min(int(self.block_size), loop.max_request_rows)
            if n <= cap:
                return loop.submit(name, X, method=method).result()
            futs = [loop.submit(name, X[s], method=method)
                    for s in _block_slices(n, cap)]
            return np.concatenate([f.result() for f in futs], axis=0)

    def _dispatch(self, method, X):
        if self.serving is not None and not sp.issparse(X):
            self._check_method(method)  # AttributeError contract first
            entry = None
            if not getattr(self, "_serving_unsupported", False):
                try:
                    name = self._serving_name()
                    entry = self.serving.registry.get(name)
                except ValueError as e:
                    if self.serving_model is not None:
                        # the user NAMED this registration; a collision or
                        # unsupported family is a config error, not a
                        # silent downgrade
                        raise
                    self._serving_unsupported = True
                    logger.warning(
                        "serving registration failed for %s; falling back "
                        "to the direct path: %s",
                        type(self._postfit_estimator).__name__, e)
            if entry is not None and method in entry.runners:
                return self._serving_call(method, X)
        return self._blockwise(self._check_method(method), X)

    def _blockwise(self, fn, X):
        """Apply ``fn`` over row blocks of ``X``.

        jax-native estimators get the whole array (their internals shard it
        over the mesh — one fused program beats any host-side blocking);
        foreign estimators run one block per host thread and the results are
        concatenated, the map_blocks analogue."""
        if _is_jax_native(self._postfit_estimator):
            return fn(X)
        X = _as_rowsliceable(X)
        n = X.shape[0]
        if n <= self.block_size:
            return fn(X)
        slices = list(_block_slices(n, self.block_size))
        with ThreadPoolExecutor(max_workers=min(8, len(slices))) as pool:
            parts = list(pool.map(lambda s: fn(X[s]), slices))
        return _concat_rows(parts)

    def predict(self, X):
        return self._dispatch("predict", X)

    def predict_proba(self, X):
        return self._dispatch("predict_proba", X)

    def predict_log_proba(self, X):
        return self._blockwise(self._check_method("predict_log_proba"), X)

    def transform(self, X):
        return self._dispatch("transform", X)

    def score(self, X, y):
        """Score via the configured scorer, else delegate
        (reference: wrappers.py:175-201)."""
        if self.scoring:
            # get_scorer passes callables through and validates names.
            return get_scorer(self.scoring)(self, X, y)
        return self._postfit_estimator.score(X, y)


class Incremental(ParallelPostFit):
    """Feed row blocks to a ``partial_fit`` estimator sequentially
    (reference: wrappers.py:275-395; chain semantics _partial.py:167-182).

    The fitted clone lives in ``estimator_``; learned attributes are copied
    onto the wrapper. Inference inherits ParallelPostFit's parallel paths.
    Use ``estimator__<param>`` naming inside grid searches
    (reference: wrappers.py:345-351).
    """

    @property
    def _postfit_estimator(self):
        check_is_fitted(self, "estimator_")
        return self.estimator_

    def _fit_for_estimator(self, estimator, X, y, **fit_kwargs):
        check_scoring(estimator, self.scoring)
        start = tic()
        if _is_jax_native(estimator) and hasattr(estimator,
                                                 "_incremental_begin"):
            # jax-native fast path: the whole block chain fuses into ONE
            # lax.scan program — no per-block host round-trip, and X may
            # already live on the mesh (no transfer at all).
            sample_weight = fit_kwargs.pop("sample_weight", None)
            if not hasattr(X, "shape"):
                X = np.asarray(X)
            step, state, y_enc = estimator._incremental_begin(
                X, y, **fit_kwargs)
            state = incremental_scan(
                step, state, X, y_enc, sample_weight=sample_weight,
                block_size=self.block_size,
            )
            estimator._incremental_finalize(state)
            logger.info("Finished fused incremental fit, %0.2f", tic() - start)
        else:
            X = _as_rowsliceable(X)
            y = None if y is None else np.asarray(y)
            n = X.shape[0]
            for i, s in enumerate(_block_slices(n, self.block_size)):
                yb = None if y is None else y[s]
                estimator.partial_fit(X[s], yb,
                                      **_slice_kwargs(fit_kwargs, s, n))
                logger.debug("partial_fit block %d (%d rows)", i, X[s].shape[0])
            logger.info("Finished incremental fit, %0.2f", tic() - start)
        copy_learned_attributes(estimator, self)
        self.estimator_ = estimator
        return self

    def fit(self, X, y=None, **fit_kwargs):
        estimator = sklearn.base.clone(self.estimator)
        return self._fit_for_estimator(estimator, X, y, **fit_kwargs)

    def partial_fit(self, X, y=None, **fit_kwargs):
        """Resume from ``estimator_`` if previously fit
        (reference: wrappers.py:375-395)."""
        estimator = getattr(self, "estimator_", None)
        if estimator is None:
            estimator = sklearn.base.clone(self.estimator)
        return self._fit_for_estimator(estimator, X, y, **fit_kwargs)


def fit(model, X, y=None, compute: bool = True,
        block_size: int = DEFAULT_BLOCK_SIZE, **kwargs):
    """Functional sequential-chain fit — API parity with the reference's
    ``_partial.fit`` (reference: _partial.py:110-182, whose ``compute=``
    picks lazy vs eager graph execution; it sits in the reference's
    positional slot so ported ``fit(model, x, y, False)`` calls bind
    correctly). Returns the fitted model (the same object, mutated, as
    sklearn's partial_fit does). ``compute`` itself is a no-op: the chain
    here is inherently eager — each block's update is the next block's
    input — and jax's async dispatch already overlaps device work with
    the host loop, which is the capability ``compute=False`` bought the
    reference."""
    del compute
    if not hasattr(model, "partial_fit"):
        raise TypeError(f"{model!r} does not implement partial_fit")
    X = _as_rowsliceable(X)
    y = None if y is None else np.asarray(y)
    if X.ndim != 2:
        raise ValueError("X must be 2-D")
    n = X.shape[0]
    for s in _block_slices(n, block_size):
        model.partial_fit(X[s], None if y is None else y[s],
                          **_slice_kwargs(kwargs, s, n))
    return model


def incremental_scan(step_fn, init_state, X, y=None, sample_weight=None,
                     block_size: int = 1024):
    """Fused incremental training for jax-native functional estimators.

    ``step_fn(state, (x_block, y_block, w_block)) -> state`` is scanned over
    fixed-size row blocks as ONE compiled XLA program (the carry is updated
    in place on device by XLA) — the TPU-native upgrade of the reference's
    serial task chain (_partial.py:167-177): same sequential semantics, no
    per-block host round-trip, no model serialization between blocks.

    ``w_block`` carries the per-row weight: ``sample_weight`` (default 1) on
    real rows, 0 on the zero-padding appended to complete the final block —
    a partial tail block is processed exactly, not dropped (fixed shapes
    under jit demand the padding; the weights make it inert).
    """
    import jax.numpy as jnp

    X = jnp.asarray(X)
    n = X.shape[0]
    if n == 0:
        raise ValueError("X has no rows")
    block_size = min(block_size, n)
    n_blocks = -(-n // block_size)  # ceil
    pad = n_blocks * block_size - n

    if sample_weight is None:
        w = jnp.ones((n,), jnp.float32)
    else:
        w = jnp.asarray(sample_weight, jnp.float32)
        if w.shape != (n,):
            raise ValueError(
                f"sample_weight shape {w.shape} != ({n},)")
    if pad:
        X = jnp.pad(X, [(0, pad)] + [(0, 0)] * (X.ndim - 1))
        w = jnp.pad(w, (0, pad))
    Xb = X.reshape(n_blocks, block_size, *X.shape[1:])
    wb = w.reshape(n_blocks, block_size)
    if y is not None:
        y = jnp.asarray(y)
        if pad:
            y = jnp.pad(y, [(0, pad)] + [(0, 0)] * (y.ndim - 1))
        # Preserve y's trailing dims: step_fn sees exactly the block shapes
        # the caller's y implies ((block_size,) for 1-D, (block_size, k) for
        # multi-output).
        yb = y.reshape(n_blocks, block_size, *y.shape[1:])
    else:
        yb = jnp.zeros((n_blocks, block_size), X.dtype)

    return _get_scan_run(step_fn)(init_state, Xb, yb, wb)


# Compiled-scan cache keyed weakly on step_fn: repeated epochs/candidates
# with a stable step function reuse one compiled program, while throwaway
# closures don't pin their captures (and compiled executables) forever the
# way a static-arg jit cache would.
_scan_cache = weakref.WeakKeyDictionary()
# Bounded strong-ref fallback for UNWEAKREFABLE step_fns (instances of
# __slots__ classes without __weakref__, various C-implemented callables):
# they used to silently skip caching and recompile the scan EVERY fit.
# Keyed by identity (two equal-looking callables are distinct programs
# anyway, since jit tracing closes over each one separately); the held
# reference is what keeps the id stable. LRU-evicted at a small bound so
# throwaway callables (and their captures + compiled executables) cannot
# accumulate forever — the failure mode the weak dict exists to avoid.
_scan_cache_strong: dict = {}  # id(step_fn) -> (step_fn, run); dicts are ordered
_SCAN_CACHE_STRONG_MAX = 32
_scan_cache_lock = threading.Lock()


def _get_scan_run(step_fn):
    try:
        return _scan_cache[step_fn]
    except (KeyError, TypeError):
        pass
    with _scan_cache_lock:
        entry = _scan_cache_strong.get(id(step_fn))
        if entry is not None and entry[0] is step_fn:
            # refresh LRU position
            _scan_cache_strong[id(step_fn)] = _scan_cache_strong.pop(
                id(step_fn))
            return entry[1]

    @jax.jit
    def run(state, Xb, yb, wb):
        def body(state, blk):
            return step_fn(state, blk), None

        state, _ = jax.lax.scan(body, state, (Xb, yb, wb))
        return state

    try:
        _scan_cache[step_fn] = run
    except TypeError:  # unweakrefable: bounded strong-ref fallback
        with _scan_cache_lock:
            _scan_cache_strong[id(step_fn)] = (step_fn, run)
            while len(_scan_cache_strong) > _SCAN_CACHE_STRONG_MAX:
                _scan_cache_strong.pop(next(iter(_scan_cache_strong)))
    return run
