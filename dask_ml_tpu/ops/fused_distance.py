"""Fused distance-reduction kernel family.

Every assignment-style hot path in this package computes the same thing:
squared Euclidean distances from n query rows to m small/replicated target
rows, immediately reduced along the target axis — a per-row min (k-means||
round updates), argmin+min (``pairwise_distances_argmin_min``, label
assignment), or argmin followed by a weighted per-target accumulation (the
k-means|| candidate-weighting contraction). The lowered-XLA formulation
writes the full (n × m) distance matrix to HBM only to immediately reduce
it — and TPU tiling lane-pads m up to 128, so even an m=8 intermediate
costs a full (n × 128) write + read. This module fuses the reduction into
the distance pass, flash-attention-style: distances for one row block are
computed on the MXU into VMEM, the *online* epilogue (min / argmin /
one-hot weight accumulation in VMEM scratch) consumes them before the
block leaves fast memory, and the (n × m) intermediate never exists.

The family (all honoring a validity mask over Y rows, so padded candidate
slots never need a ``jnp.inf`` re-masking pass over an (n × m) matrix):

- :func:`fused_rowwise_min` — per-row min squared distance.
- :func:`fused_argmin_min` — per-row (argmin index, min squared distance).
- :func:`fused_argmin_min2` — per-row (argmin index, min squared distance,
  SECOND-best squared distance) — the seeding primitive for Elkan/Yinyang
  center-movement bounds (models/kmeans.py ``lloyd_loop_bounded``): the
  best distance seeds the upper bound, the second-best seeds every group
  lower bound.
- :func:`fused_argmin_weight` — per-row argmin plus the per-target sum of
  row weights (the candidate-weighting / M-step-count contraction).
- :func:`fused_argmin_min_sketched` — argmin + full-space min against
  SKETCHED targets (a shared transform-column support + dense values —
  the fast-transform center sketches of ops/fast_transform.py): the
  contraction runs over the p support columns, O(n·k·p) instead of
  O(n·k·d) (docs/kernels.md, "Sketched assignment").

Row-level work skipping (``row_need=``): :func:`fused_rowwise_min` and
:func:`fused_argmin_min2` accept an optional boolean ``row_need`` over X
rows. The distance work is then skipped BLOCK-wise — X streams through in
``_FUSED_BLK``-row blocks, and a block none of whose rows need evaluation
never pays for its distance pass: the XLA path runs a ``lax.map`` over row
blocks with a scalar ``lax.cond`` per block (the batched-cells freeze
precedent — map keeps the predicate scalar, so skipped blocks genuinely
don't execute the matmul), and the pallas path predicates each grid step
with ``pl.when``. Skipped rows return the identity of the consumer's
reduction (``+inf`` for the incremental-min consumer, zeros for the
argmin consumers — overlay with :func:`row_block_evaluated`). This is the
mechanism the bound-maintaining Lloyd loop and the k-means|| rounds use
to not compute distances for rows whose bounds prove the answer
unchanged (docs/kernels.md, "Bound-based pruning").

Each has three implementations selected by ``kernel=``:

- ``"xla"`` — the jnp reference: one expression XLA lowers itself. This is
  also the family's semantic ground truth; the property tests pin the
  pallas path against it bit-for-bit where FP arithmetic is exact.
- ``"pallas"`` — the tiled single-pass kernel. Off-TPU it runs in Pallas
  interpret mode (slow, CPU CI only).
- ``"auto"`` — the measured-dispatch default, following the
  ``_pallas_auto_wins`` precedent from the Lloyd kernel
  (models/kmeans.py): pallas only on TPU, only in regimes where the fusion
  is expected to win (:func:`_fused_auto_wins`), XLA everywhere else.
  ``bench.py --fused`` measures fused-vs-unfused over an (n, m, d) grid to
  populate/validate the thresholds — see docs/kernels.md.

Score convention (shared by ALL implementations so ties break identically):
the reduction runs over ``s_j = |y_j|² − 2·x·y_j`` — the per-row-constant
``|x|²`` term does not affect the argmin and is added back (then clamped at
0 against cancellation, same guard as ``sq_euclidean``) only to the
returned min VALUE. Masked Y rows score ``+inf`` and can never win; when
every row is masked, argmin is 0 and the min is ``+inf`` (the jnp
``argmin``-over-all-inf convention).

Sharding: the XLA path is a plain traced expression — GSPMD partitions it
like any other op. A ``pallas_call`` has no GSPMD partitioning rule, so
for sharded inputs the pallas path must run *per shard*: pass ``mesh=`` and
the call is wrapped in ``shard_map`` over the data axis (row-wise outputs
stay sharded; the weight accumulation psums). Without a mesh, auto never
selects pallas on a multi-device backend — replicating the operands into
an unpartitioned kernel would gather the shard.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

# lanes per X-row block streamed through VMEM. At the support bounds
# (m=1024, d=512) one grid step holds Xb (2 MB) + Y (2 MB) + scores (4 MB)
# + the one-hot temporary (4 MB) — comfortable margin under the ~16 MB
# VMEM budget. Module-level so tests can shrink it to force multi-block
# grids on small inputs.
_FUSED_BLK = 1024


def _fused_supported(m: int, d: int) -> bool:
    """Shapes the kernel handles with comfortable VMEM margins: Y and one
    (m × blk) score block must both sit in VMEM alongside the X block.
    Beyond the bound an explicit ``kernel='pallas'`` raises; ``'auto'``
    silently keeps XLA."""
    return 1 <= m <= 1024 and 1 <= d <= 512


def _fused_auto_wins(n: int, m: int, d: int, dtype, mesh) -> bool:
    """The regimes where ``kernel='auto'`` selects the fused pallas path.

    PROVISIONAL, roofline-derived — to be re-cut from measurement the same
    way the Lloyd kernel's ``_pallas_auto_wins`` table was (bench.py
    ``--fused`` emits fused-vs-unfused wall times over an (n, m, d) grid
    for exactly this purpose; docs/kernels.md records the methodology).
    The reasoning: the unfused path writes + re-reads an (n × m) f32
    intermediate that TPU tiling lane-pads to (n × ⌈m/128⌉·128) — for any
    m ≤ 128 that is 1 KiB of extra HBM traffic per row, several times the
    row itself at the d ≤ 128 shapes these consumers run (the KDD init's
    d=41, assignment/embedding d=k). The fusion can only pay once n is
    large enough to amortize Mosaic's pipeline spin-up (the PR-1 lesson:
    halving logical traffic loses when the kernel can't saturate HBM on a
    small grid), and the rule deliberately keeps XLA at wide d, where the
    X read dominates the intermediate and the Lloyd sweep measured f32
    parity bands — widen only once the grid shows a win there.

    TPU only — off-TPU the kernel runs in interpret mode, where the
    unfused XLA path always wins (the CPU CI mesh exercises pallas through
    the property tests, never through auto).

    Bench-measured regimes in the decision cache
    (``parallel/decisions.py``) override the roofline rule point-wise; the
    support/mesh guards above stay outside the cache (correctness, not
    speed).
    """
    if not _fused_supported(m, d):
        return False
    if mesh is None and jax.device_count() > 1:
        return False  # no GSPMD rule for pallas_call: would gather the shard
    from dask_ml_tpu.parallel import decisions

    return decisions.lookup(
        "fused.distance.pallas",
        {"n": n, "m": m, "d": d, "dtype": str(jnp.dtype(dtype))},
        fallback=(jax.default_backend() == "tpu"
                  and n >= (1 << 18) and m >= 16 and d <= 128))


def _check_kernel(kernel: str, m: int, d: int) -> None:
    if kernel not in ("auto", "pallas", "xla"):
        raise ValueError(f"kernel must be auto|pallas|xla, got {kernel!r}")
    if kernel == "pallas" and not _fused_supported(m, d):
        raise ValueError(
            f"kernel='pallas' supports 1<=m<=1024, d<=512; got m={m}, d={d}")


def _use_pallas(kernel, n, m, d, dtype, mesh):
    _check_kernel(kernel, m, d)
    return kernel == "pallas" or (
        kernel == "auto" and _fused_auto_wins(n, m, d, dtype, mesh))


def _row_specs(mesh):
    """The family's shard_map specs for ``mesh`` —
    ``(P(axes, None), P(axes), P(None, axes))`` for (n, d) / (n,) /
    (1, n) row-sharded operands, where ``axes`` is ``'data'`` on a flat
    mesh and ``('pod', 'chip')`` on a hierarchical one
    (parallel/hierarchy.py): the wrappers below are mesh-level-agnostic."""
    from dask_ml_tpu.parallel.mesh import data_axes

    axes = data_axes(mesh)
    a = axes[0] if len(axes) == 1 else axes
    return P(a, None), P(a), P(None, a)


def _row_sumsq(X):
    """Per-row Σx² as a ones-matmul, f32-accumulated — the SAME op (and
    accumulation order) the kernel uses in VMEM, so reference and fused
    values agree bit-for-bit wherever the arithmetic is exact."""
    Xf = X.astype(jnp.float32)
    ones = jnp.ones((1, X.shape[1]), jnp.float32)
    return jax.lax.dot_general(
        ones, Xf * Xf, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)[0]  # (n,)


def _scores_ref(X, Y, mask):
    """(n, m) reduction scores ``|y|² − 2·x·y`` with masked rows at +inf —
    the reference the pallas kernel must reproduce (same compute dtype:
    Y is cast to X's dtype for the MXU, accumulation in f32).

    Precision audit (docs/precision.md): the ``|y|²`` term comes from the
    ORIGINAL Y in f32, not from the compute-dtype copy ``Yc``. The score
    is a difference of two O(|y|²) terms, so an error in the norm lands
    directly on the (possibly tiny) distance gap: with bf16 X, rounding Y
    to bf16 BEFORE squaring perturbs ``|y|²`` by up to ~0.8% — enough to
    flip an argmin between near-duplicate centers whose separation is
    below bf16 resolution (pinned by
    ``tests/test_precision.py::test_fused_bf16_near_duplicate_centers``).
    The ``−2x·y`` term keeps the compute-dtype operands (that is the MXU
    path being bought), always accumulating f32."""
    Yc = Y.astype(X.dtype)
    y2 = jnp.sum(Y.astype(jnp.float32) ** 2, axis=1)  # (m,) from ORIGINAL Y
    prod = jax.lax.dot_general(
        X, Yc, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)  # (n, m)
    s = y2[None, :] - 2.0 * prod
    if mask is not None:
        s = jnp.where(mask[None, :], s, jnp.inf)
    return s


def _min_ref(X, Y, mask):
    s = _scores_ref(X, Y, mask)
    return jnp.maximum(jnp.min(s, axis=1) + _row_sumsq(X), 0.0)


def _argmin_min_ref(X, Y, mask):
    s = _scores_ref(X, Y, mask)
    idx = jnp.argmin(s, axis=1).astype(jnp.int32)
    mind = jnp.maximum(jnp.min(s, axis=1) + _row_sumsq(X), 0.0)
    return idx, mind


def _argmin_min_sk_ref(Zp, vals, x2, mask):
    """Sketched-assignment reference: targets live in the transform space
    as dense ``vals`` (k, p) on one shared column support — see
    ops/fast_transform.py. ``Zp`` (n, p) is the data already restricted
    to the support columns and the reduction contracts over them only
    (that is the O(n·k·p) being bought), which is exact for the ARGMIN:
    restricted and full-space distances to support-sparse targets differ
    by the per-row constant ``|z_offsupport|²``. The returned VALUE is
    the true full-space squared distance — the add-back term ``x2`` (n,)
    is the caller-computed full-space ``|x − μ|²``, not the restricted
    block's own norm. Same mask/tie-break/all-masked contracts as the
    rest of the family; the support entries must be distinct
    (sketch_project guarantees it) or the ``|y|²`` term double-counts."""
    s = _scores_ref(Zp, vals, mask)
    idx = jnp.argmin(s, axis=1).astype(jnp.int32)
    mind = jnp.maximum(jnp.min(s, axis=1) + x2, 0.0)
    return idx, mind


def _argmin_min2_ref(X, Y, mask):
    """(argmin, min d², second-best d²) — the reduction scores' best value
    and the best value with the argmin column masked out. With m == 1 (or
    everything-but-best masked) the second-best is ``+inf``, the natural
    "no competitor" value: a bound seeded from it never forces a
    re-evaluation."""
    s = _scores_ref(X, Y, mask)
    idx = jnp.argmin(s, axis=1).astype(jnp.int32)
    m = Y.shape[0]
    s2 = jnp.where(jnp.arange(m, dtype=jnp.int32)[None, :] == idx[:, None],
                   jnp.inf, s)
    x2 = _row_sumsq(X)
    mind = jnp.maximum(jnp.min(s, axis=1) + x2, 0.0)
    mind2 = jnp.maximum(jnp.min(s2, axis=1) + x2, 0.0)
    return idx, mind, mind2


def _row_blocks(n: int):
    """(n_blocks, padded_n) for the ``row_need`` blocking — one definition
    shared by the XLA blocked path, the pallas grid, and
    :func:`row_block_evaluated`, so "which rows share a skip decision" can
    never diverge between implementations."""
    blk = _FUSED_BLK
    nb = (n + blk - 1) // blk
    return nb, nb * blk


def row_block_evaluated(row_need):
    """Per-row "this row's block was evaluated" mask for a ``row_need``
    vector: True for every row sharing a ``_FUSED_BLK`` block with at
    least one needed row. Consumers overlay block-skipped outputs with
    their carried values through exactly this mask — evaluated blocks
    recompute ALL their rows (the recomputed values are the full
    answers, so overwriting un-needed rows in an evaluated block is free
    tightening, never a wrong value)."""
    n = row_need.shape[0]
    nb, n_pad = _row_blocks(n)
    need = row_need
    if n_pad != n:
        need = jnp.pad(need, (0, n_pad - n))
    ev = jnp.any(need.reshape(nb, _FUSED_BLK), axis=1)
    return jnp.repeat(ev, _FUSED_BLK)[:n]


def _blocked_xla(X, Y, mask, row_need, epilogue: str):
    """The XLA row-skipping lowering: ``lax.map`` over ``_FUSED_BLK``-row
    blocks with a scalar ``lax.cond`` per block, so a fully-skippable
    block's distance matmul genuinely does not execute (the
    `_batched_cells_impl` freeze precedent — under ``vmap`` the cond would
    lower to a both-branches select and skip nothing). Evaluated blocks
    run the SAME reference expression as the unskipped path on their row
    slice, so evaluated rows reproduce the full-array answer; skipped
    blocks return the consumer's reduction identity (+inf for ``min``,
    zeros for ``argmin_min2`` — overlaid via :func:`row_block_evaluated`).
    """
    n, d = X.shape
    nb, n_pad = _row_blocks(n)
    blk = _FUSED_BLK
    Xp = jnp.pad(X, ((0, n_pad - n), (0, 0))) if n_pad != n else X
    needp = (jnp.pad(row_need, (0, n_pad - n))
             if n_pad != n else row_need)
    Xb = Xp.reshape(nb, blk, d)
    needb = needp.reshape(nb, blk)

    if epilogue == "min":
        def one(args):
            xb, nd = args
            return jax.lax.cond(
                jnp.any(nd),
                lambda x: _min_ref(x, Y, mask),
                lambda x: jnp.full((blk,), jnp.inf, jnp.float32),
                xb)

        out = jax.lax.map(one, (Xb, needb))
        return out.reshape(-1)[:n]

    def one(args):
        xb, nd = args
        return jax.lax.cond(
            jnp.any(nd),
            lambda x: _argmin_min2_ref(x, Y, mask),
            lambda x: (jnp.zeros((blk,), jnp.int32),
                       jnp.zeros((blk,), jnp.float32),
                       jnp.zeros((blk,), jnp.float32)),
            xb)

    idx, mind, mind2 = jax.lax.map(one, (Xb, needb))
    return (idx.reshape(-1)[:n], mind.reshape(-1)[:n],
            mind2.reshape(-1)[:n])


def _blocked_xla_sk(Zp, vals, x2, mask, row_need):
    """The sketched analogue of :func:`_blocked_xla` (same ``lax.map`` +
    scalar ``lax.cond`` blocking, same skip identities — zeros for the
    argmin consumer, overlaid via :func:`row_block_evaluated`)."""
    n, p = Zp.shape
    nb, n_pad = _row_blocks(n)
    blk = _FUSED_BLK
    Zpp = jnp.pad(Zp, ((0, n_pad - n), (0, 0))) if n_pad != n else Zp
    x2p = jnp.pad(x2, (0, n_pad - n)) if n_pad != n else x2
    needp = (jnp.pad(row_need, (0, n_pad - n))
             if n_pad != n else row_need)
    Zb = Zpp.reshape(nb, blk, p)
    x2b = x2p.reshape(nb, blk)
    needb = needp.reshape(nb, blk)

    def one(args):
        zb, xb, nd = args
        return jax.lax.cond(
            jnp.any(nd),
            lambda z, x: _argmin_min_sk_ref(z, vals, x, mask),
            lambda z, x: (jnp.zeros((blk,), jnp.int32),
                          jnp.zeros((blk,), jnp.float32)),
            zb, xb)

    idx, mind = jax.lax.map(one, (Zb, x2b, needb))
    return idx.reshape(-1)[:n], mind.reshape(-1)[:n]


def _argmin_weight_ref(X, w, Y, mask):
    s = _scores_ref(X, Y, mask)
    idx = jnp.argmin(s, axis=1).astype(jnp.int32)
    onehot = (jnp.arange(Y.shape[0], dtype=jnp.int32)[None, :]
              == idx[:, None])
    # contraction over the (possibly sharded) sample axis — GSPMD inserts
    # the psum; a scatter-add segment_sum serializes on TPU
    cw = jax.lax.dot_general(
        w.astype(jnp.float32), onehot.astype(jnp.float32),
        (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)  # (m,)
    if mask is not None:
        cw = jnp.where(mask, cw, 0.0)
    return idx, cw


# ---------------------------------------------------------------------------
# the tiled single-pass kernel
# ---------------------------------------------------------------------------


def _fused_pallas(X, Y, maskf, w2d, epilogue: str, need2d=None, x2d=None):
    """One pass over row blocks of X with the whole (m, d) Y resident in
    VMEM. Per block: scores on the MXU in (m, blk) layout (m on sublanes —
    the block's minor dim stays the 128-lane-aligned ``blk``), then the
    online epilogue on the VPU. Row-wise outputs are written per grid step;
    the (m,) weight accumulation lives in VMEM scratch and is written once
    on the final step (the Lloyd kernel's accumulator discipline —
    revisited output blocks would serialize the loop on tiny DMAs).

    ``maskf`` is the (m, 1) f32 validity mask (1=real row); ``w2d`` the
    (1, n) f32 row weights (``epilogue='argmin_weight'`` only); ``need2d``
    the optional (1, n) f32 row-need vector (``'min'``/``'argmin_min'``/
    ``'argmin_min2'``): grid steps none of whose rows need evaluation skip
    the matmul + epilogue under ``pl.when`` and write the reduction
    identity instead — only the tiny need-block read reaches VMEM for a
    skipped block. ``x2d`` (optional (1, n) f32, ``'argmin_min'`` only) is
    an externally-computed per-row ``|x|²`` used in place of the block's
    own: the sketched-assignment consumer contracts over the SUPPORT
    columns but owes the caller full-space squared distances, so the
    add-back term comes from the full transformed row, not the gathered
    block (:func:`fused_argmin_min_sketched`).
    """
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, d = Y.shape
    n = X.shape[0]
    blk = _FUSED_BLK
    grid = (n + blk - 1) // blk
    interpret = jax.default_backend() != "tpu"

    def kernel(y_ref, y2_ref, mask_ref, x_ref, *rest):
        if epilogue == "argmin_weight":
            w_ref, am_ref, cw_ref, acc_cw = rest
        elif epilogue == "argmin_min2":
            if need2d is not None:
                need_ref, am_ref, mn_ref, mn2_ref = rest
            else:
                am_ref, mn_ref, mn2_ref = rest
        elif epilogue == "argmin_min":
            rrest = list(rest)
            need_ref = rrest.pop(0) if need2d is not None else None
            x2_ref = rrest.pop(0) if x2d is not None else None
            am_ref, mn_ref = rrest
        else:  # "min"
            if need2d is not None:
                need_ref, mn_ref = rest
            else:
                (mn_ref,) = rest
        i = pl.program_id(0)

        col = i * blk + jax.lax.broadcasted_iota(jnp.int32, (1, blk), 1)
        valid_col = col < n

        if need2d is not None:
            # OOB columns of the final partial need block are undefined —
            # select them to 0 before the any-reduction (0·NaN discipline)
            needv = jnp.where(valid_col, need_ref[:], 0.0)  # (1, blk)
            evaluate = jnp.sum(needv) > 0.0

            @pl.when(jnp.logical_not(evaluate))
            def _():
                # reduction identities for a skipped block: +inf for the
                # incremental-min consumer (minimum(prev, inf) is a
                # no-op), zeros for the argmin consumer (overlaid via
                # row_block_evaluated)
                if epilogue == "min":
                    mn_ref[:] = jnp.full_like(mn_ref, jnp.inf)
                else:
                    am_ref[:] = jnp.zeros_like(am_ref)
                    mn_ref[:] = jnp.zeros_like(mn_ref)
                    if epilogue == "argmin_min2":
                        mn2_ref[:] = jnp.zeros_like(mn2_ref)

        def block_scores():
            # the ONE definition of the block's masked scores, shared by
            # every epilogue (drift here is exactly the divergence the
            # module's single-definition discipline forbids)
            Yb = y_ref[:]  # (m, d), X's compute dtype
            # zero OOB columns of the final partial block with a SELECT:
            # their contents are undefined (NaN in interpret mode) and
            # 0·NaN = NaN would survive a multiplicative mask into the
            # matmul contraction
            Xb = jnp.where(
                jax.lax.broadcasted_iota(jnp.int32, (blk, 1), 0)
                + i * blk < n,
                x_ref[:], 0)  # (blk, d)

            # |y|² arrives precomputed in f32 from the ORIGINAL Y (same
            # convention as _scores_ref — see its precision-audit note),
            # so a bf16 compute dtype never degrades the norm term
            y2 = y2_ref[:]  # (m, 1) f32
            prod = jax.lax.dot_general(
                Yb, Xb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)  # (m, blk) on the MXU
            scores = y2 - 2.0 * prod
            scores = jnp.where(mask_ref[:] > 0, scores, jnp.inf)
            return Xb, scores

        def row_x2(Xb):
            # per-row |x|² as a ones-matmul, f32 — the SAME op order as
            # _row_sumsq so values match the reference bit-for-bit where
            # exact
            ones = jnp.ones((1, d), jnp.float32)
            Xf = Xb.astype(jnp.float32)
            return jax.lax.dot_general(
                ones, Xf * Xf, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)  # (1, blk)

        def compute():
            Xb, scores = block_scores()

            if epilogue == "argmin_min2":
                best = jnp.argmin(scores, axis=0, keepdims=True)  # (1, blk)
                am_ref[:] = best.astype(jnp.int32)
                kiota = jax.lax.broadcasted_iota(jnp.int32, (m, blk), 0)
                s2 = jnp.where(kiota == best, jnp.inf, scores)
                x2 = row_x2(Xb)
                mn_ref[:] = jnp.maximum(
                    jnp.min(scores, axis=0, keepdims=True) + x2, 0.0)
                mn2_ref[:] = jnp.maximum(
                    jnp.min(s2, axis=0, keepdims=True) + x2, 0.0)
                return

            if epilogue == "argmin_min":
                best = jnp.argmin(scores, axis=0, keepdims=True)
                am_ref[:] = best.astype(jnp.int32)
            # min value: add the per-row |x|² back, clamp cancellation at
            # 0. The sketched consumer supplies its own full-space |x|²
            # (select OOB lanes of the final partial block to 0 — their
            # contents are undefined, the 0·NaN discipline again).
            if x2d is not None:
                x2 = jnp.where(valid_col, x2_ref[:], 0.0)
            else:
                x2 = row_x2(Xb)
            mn_ref[:] = jnp.maximum(
                jnp.min(scores, axis=0, keepdims=True) + x2, 0.0)

        if (epilogue in ("min", "argmin_min", "argmin_min2")
                and need2d is not None):
            pl.when(evaluate)(compute)
            return
        if epilogue in ("min", "argmin_min", "argmin_min2"):
            compute()
            return

        _, scores = block_scores()

        if epilogue == "argmin_weight":
            best = jnp.argmin(scores, axis=0, keepdims=True)  # (1, blk)
            am_ref[:] = best.astype(jnp.int32)

            @pl.when(i == 0)
            def _():
                acc_cw[:] = jnp.zeros_like(acc_cw)

            wv = jnp.where(valid_col, w_ref[:], 0.0)  # (1, blk)
            kiota = jax.lax.broadcasted_iota(jnp.int32, (m, blk), 0)
            oh_w = (kiota == best).astype(jnp.float32) * wv  # (m, blk)
            acc_cw[:] += jnp.sum(oh_w, axis=1, keepdims=True)  # (m, 1)

            @pl.when(i == grid - 1)
            def _():
                # masked rows can still absorb weight in the all-masked
                # degenerate case (argmin of all-inf is 0) — zero them,
                # matching the reference's final where(mask, cw, 0)
                cw_ref[:] = acc_cw[:] * jnp.minimum(mask_ref[:], 1.0)
            return

    y_spec = pl.BlockSpec((m, d), lambda i: (0, 0), memory_space=pltpu.VMEM)
    col_spec = pl.BlockSpec((m, 1), lambda i: (0, 0),
                            memory_space=pltpu.VMEM)
    x_spec = pl.BlockSpec((blk, d), lambda i: (i, 0),
                          memory_space=pltpu.VMEM)
    row_spec = pl.BlockSpec((1, blk), lambda i: (0, i),
                            memory_space=pltpu.VMEM)

    Yc = Y.astype(X.dtype)
    # f32 norms of the ORIGINAL Y — the kernel's one full-precision input
    # (see _scores_ref's precision-audit note)
    y2f = jnp.sum(Y.astype(jnp.float32) ** 2, axis=1).reshape(m, 1)
    if epilogue == "argmin_weight":
        am, cw = pl.pallas_call(
            kernel,
            grid=(grid,),
            in_specs=[y_spec, col_spec, col_spec, x_spec, row_spec],
            out_specs=[
                row_spec,
                pl.BlockSpec((m, 1), lambda i: (0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((1, n), jnp.int32),
                jax.ShapeDtypeStruct((m, 1), jnp.float32),
            ],
            scratch_shapes=[pltpu.VMEM((m, 1), jnp.float32)],
            interpret=interpret,
        )(Yc, y2f, maskf, X, w2d)
        return am[0], cw[:, 0]
    if epilogue == "argmin_min":
        in_specs = [y_spec, col_spec, col_spec, x_spec]
        args = [Yc, y2f, maskf, X]
        if need2d is not None:
            in_specs.append(row_spec)
            args.append(need2d)
        if x2d is not None:
            in_specs.append(row_spec)
            args.append(x2d)
        am, mn = pl.pallas_call(
            kernel,
            grid=(grid,),
            in_specs=in_specs,
            out_specs=[row_spec, row_spec],
            out_shape=[
                jax.ShapeDtypeStruct((1, n), jnp.int32),
                jax.ShapeDtypeStruct((1, n), jnp.float32),
            ],
            interpret=interpret,
        )(*args)
        return am[0], mn[0]
    if epilogue == "argmin_min2":
        in_specs = [y_spec, col_spec, col_spec, x_spec]
        args = [Yc, y2f, maskf, X]
        if need2d is not None:
            in_specs.append(row_spec)
            args.append(need2d)
        am, mn, mn2 = pl.pallas_call(
            kernel,
            grid=(grid,),
            in_specs=in_specs,
            out_specs=[row_spec, row_spec, row_spec],
            out_shape=[
                jax.ShapeDtypeStruct((1, n), jnp.int32),
                jax.ShapeDtypeStruct((1, n), jnp.float32),
                jax.ShapeDtypeStruct((1, n), jnp.float32),
            ],
            interpret=interpret,
        )(*args)
        return am[0], mn[0], mn2[0]
    in_specs = [y_spec, col_spec, col_spec, x_spec]
    args = [Yc, y2f, maskf, X]
    if need2d is not None:
        in_specs.append(row_spec)
        args.append(need2d)
    mn = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=in_specs,
        out_specs=row_spec,
        out_shape=jax.ShapeDtypeStruct((1, n), jnp.float32),
        interpret=interpret,
    )(*args)
    return mn[0]


def _maskf(mask, m):
    if mask is None:
        return jnp.ones((m, 1), jnp.float32)
    return mask.astype(jnp.float32).reshape(m, 1)


# ---------------------------------------------------------------------------
# public family
# ---------------------------------------------------------------------------


def fused_rowwise_min(X, Y, mask=None, *, kernel: str = "auto", mesh=None,
                      row_need=None):
    """Per-row ``min_j d²(x_i, y_j)`` over valid Y rows, shape (n,) f32.

    Masked rows score +inf; all-masked returns +inf per row (so an
    incremental-min consumer's ``jnp.minimum(prev, ...)`` is a no-op for
    empty rounds). ``mesh`` wraps the pallas path in ``shard_map`` over
    the data axis for row-sharded X (see module docstring).

    ``row_need`` (optional (n,) bool) enables BLOCK-wise row skipping:
    ``_FUSED_BLK``-row blocks with no needed row never execute their
    distance pass and return ``+inf`` for every row (the incremental-min
    identity — a skipped row's ``jnp.minimum(prev, out)`` keeps ``prev``
    exactly). Rows sharing a block with a needed row are evaluated and
    return the full answer. With a mesh, ``row_need`` is sharded with X
    and the skip decisions are per-shard blocks."""
    m, d = Y.shape
    use_pallas = _use_pallas(kernel, X.shape[0], m, d, X.dtype, mesh)
    if row_need is None:
        if not use_pallas:
            return _min_ref(X, Y, mask)
        maskf = _maskf(mask, m)
        if mesh is None:
            return _fused_pallas(X, Y, maskf, None, "min")
        from dask_ml_tpu.parallel.mesh import shard_map

        d2, d1, _ = _row_specs(mesh)
        fn = shard_map(
            lambda Xl, Yl, ml: _fused_pallas(Xl, Yl, ml, None, "min"),
            mesh=mesh, in_specs=(d2, P(), P()),
            out_specs=d1, check_vma=False)
        return fn(X, Y, maskf)
    maskf = _maskf(mask, m)
    if not use_pallas:
        if mesh is None:
            return _blocked_xla(X, Y, mask, row_need, "min")
        # the blocked lax.map must run PER SHARD (a global block any()
        # would all-reduce per block under GSPMD) — same shard_map shape
        # as the pallas path
        from dask_ml_tpu.parallel.mesh import shard_map

        d2, d1, _ = _row_specs(mesh)
        fn = shard_map(
            lambda Xl, nl: _blocked_xla(Xl, Y, mask, nl, "min"),
            mesh=mesh, in_specs=(d2, d1),
            out_specs=d1, check_vma=False)
        return fn(X, row_need)
    need2d = row_need.astype(jnp.float32)[None, :]
    if mesh is None:
        return _fused_pallas(X, Y, maskf, None, "min", need2d=need2d)
    from dask_ml_tpu.parallel.mesh import shard_map

    d2, d1, d1m = _row_specs(mesh)
    fn = shard_map(
        lambda Xl, Yl, ml, nl: _fused_pallas(Xl, Yl, ml, None, "min",
                                             need2d=nl),
        mesh=mesh,
        in_specs=(d2, P(), P(), d1m),
        out_specs=d1, check_vma=False)
    return fn(X, Y, maskf, need2d)


def fused_argmin_min(X, Y, mask=None, *, kernel: str = "auto", mesh=None):
    """Per-row (argmin index int32, min squared distance f32) over valid
    Y rows — the assignment primitive. Ties break to the lowest index,
    identically across implementations."""
    m, d = Y.shape
    if not _use_pallas(kernel, X.shape[0], m, d, X.dtype, mesh):
        return _argmin_min_ref(X, Y, mask)
    maskf = _maskf(mask, m)
    if mesh is None:
        return _fused_pallas(X, Y, maskf, None, "argmin_min")
    from dask_ml_tpu.parallel.mesh import shard_map

    d2, d1, _ = _row_specs(mesh)
    fn = shard_map(
        lambda Xl, Yl, ml: _fused_pallas(Xl, Yl, ml, None, "argmin_min"),
        mesh=mesh, in_specs=(d2, P(), P()),
        out_specs=(d1, d1), check_vma=False)
    return fn(X, Y, maskf)


def fused_argmin_min_sketched(Z, vals, support=None, mask=None, *,
                              x2=None, kernel: str = "auto", mesh=None,
                              row_need=None):
    """Per-row (argmin index int32, min FULL-SPACE squared distance f32)
    against SKETCHED targets ``vals`` (k, p) living on one shared
    transform-column support (see ops/fast_transform.py). The
    contraction runs over the p support columns — O(n·k·p) instead of
    O(n·k·d) — which is exact for the argmin (restricted and full
    distances differ per row by the constant off-support energy); the
    returned value is the true full-space d² (the full-space ``|x − μ|²``
    is added back, then clamped at 0).

    Two input modes. With ``support`` (p,) int32 (entries distinct),
    ``Z`` (n, d_pad) is the fully fast-transformed data
    (:func:`~dask_ml_tpu.ops.fast_transform.ft_apply`) and the gather +
    full-row ``|z|²`` happen here (both row-wise, so GSPMD shards them
    with Z). With ``support=None``, ``Z`` IS the already-restricted
    (n, p) block — the staging that matters in production, where the
    thin transform slice is applied as one matmul
    (:func:`~dask_ml_tpu.ops.fast_transform.support_matrix`) and the
    full (n, d_pad) array never exists — and ``x2`` (n,) f32, the
    caller's full-space ``|x − μ|²``, is then REQUIRED (orthogonality
    makes it equal to the untaken ``|z|²``). ``x2`` may also be passed
    alongside ``support`` to skip the recompute.

    Same family contracts as :func:`fused_argmin_min`: ties break to the
    lowest index identically across implementations, masked target rows
    never win, all-masked returns (0, +inf). ``row_need`` enables the
    block-wise row skipping of :func:`fused_argmin_min2` (skipped blocks
    return zeros — overlay via :func:`row_block_evaluated`). The pallas
    path keeps the gather OUTSIDE the kernel (Mosaic has no dynamic lane
    gather) and feeds the standard argmin_min kernel at (n, k, p) with
    the full-space norm as an extra row input — so the in-kernel matmul
    really is the p-wide one, and auto dispatch reuses the measured
    ``fused.distance.pallas`` regime table at the restricted shape.
    Whether sketched assignment beats EXACT assignment at a given
    (n, k, d, p) is a different question, answered by the
    ``kmeans.sketched.assign`` decision rule
    (models/kmeans.py ``sketched_assign_wins``)."""
    k, p = vals.shape
    if support is not None:
        Zp = jnp.take(Z, support, axis=1)
        if x2 is None:
            x2 = _row_sumsq(Z)
    else:
        if x2 is None:
            raise ValueError(
                "fused_argmin_min_sketched: support=None means Z is the "
                "restricted (n, p) block; the full-space |x - mu|^2 must "
                "then be supplied via x2=")
        Zp = Z
    use_pallas = _use_pallas(kernel, Zp.shape[0], k, p, Zp.dtype, mesh)
    if row_need is None:
        if not use_pallas:
            return _argmin_min_sk_ref(Zp, vals, x2, mask)
        maskf = _maskf(mask, k)
        x2d = x2[None, :]
        if mesh is None:
            return _fused_pallas(Zp, vals, maskf, None, "argmin_min",
                                 x2d=x2d)
        from dask_ml_tpu.parallel.mesh import shard_map

        d2, d1, d1m = _row_specs(mesh)
        fn = shard_map(
            lambda Zl, Yl, ml, xl: _fused_pallas(Zl, Yl, ml, None,
                                                 "argmin_min", x2d=xl),
            mesh=mesh, in_specs=(d2, P(), P(), d1m),
            out_specs=(d1, d1), check_vma=False)
        return fn(Zp, vals, maskf, x2d)
    if not use_pallas:
        if mesh is None:
            return _blocked_xla_sk(Zp, vals, x2, mask, row_need)
        from dask_ml_tpu.parallel.mesh import shard_map

        d2, d1, _ = _row_specs(mesh)
        fn = shard_map(
            lambda Zl, xl, nl: _blocked_xla_sk(Zl, vals, xl, mask, nl),
            mesh=mesh, in_specs=(d2, d1, d1),
            out_specs=(d1, d1), check_vma=False)
        return fn(Zp, x2, row_need)
    maskf = _maskf(mask, k)
    x2d = x2[None, :]
    need2d = row_need.astype(jnp.float32)[None, :]
    if mesh is None:
        return _fused_pallas(Zp, vals, maskf, None, "argmin_min",
                             need2d=need2d, x2d=x2d)
    from dask_ml_tpu.parallel.mesh import shard_map

    d2, d1, d1m = _row_specs(mesh)
    fn = shard_map(
        lambda Zl, Yl, ml, nl, xl: _fused_pallas(
            Zl, Yl, ml, None, "argmin_min", need2d=nl, x2d=xl),
        mesh=mesh, in_specs=(d2, P(), P(), d1m, d1m),
        out_specs=(d1, d1), check_vma=False)
    return fn(Zp, vals, maskf, need2d, x2d)


def fused_argmin_min2(X, Y, mask=None, *, kernel: str = "auto", mesh=None,
                      row_need=None):
    """Per-row (argmin index int32, min squared distance f32, SECOND-best
    squared distance f32) over valid Y rows — the bound-seeding primitive:
    the best distance seeds an Elkan-style upper bound on the assigned
    center, the second-best seeds the lower bound of every Yinyang center
    group (the global second-best lower-bounds the per-group minimum over
    non-assigned centers for every group at once — see
    models/kmeans.py ``lloyd_loop_bounded``).

    Same contracts as the rest of the family: ties break to the lowest
    index identically across implementations, masked Y rows never win,
    all-masked returns (0, +inf, +inf), a single valid row returns
    second-best ``+inf`` (no competitor — a bound seeded from it never
    forces re-evaluation). ``row_need`` enables block-wise row skipping:
    blocks with no needed row skip the distance pass and return zeros —
    overlay skipped rows with carried values via
    :func:`row_block_evaluated`."""
    m, d = Y.shape
    use_pallas = _use_pallas(kernel, X.shape[0], m, d, X.dtype, mesh)
    if row_need is None:
        if not use_pallas:
            return _argmin_min2_ref(X, Y, mask)
        maskf = _maskf(mask, m)
        if mesh is None:
            return _fused_pallas(X, Y, maskf, None, "argmin_min2")
        from dask_ml_tpu.parallel.mesh import shard_map

        d2, d1, _ = _row_specs(mesh)
        fn = shard_map(
            lambda Xl, Yl, ml: _fused_pallas(Xl, Yl, ml, None,
                                             "argmin_min2"),
            mesh=mesh, in_specs=(d2, P(), P()),
            out_specs=(d1, d1, d1),
            check_vma=False)
        return fn(X, Y, maskf)
    if not use_pallas:
        if mesh is None:
            return _blocked_xla(X, Y, mask, row_need, "argmin_min2")
        from dask_ml_tpu.parallel.mesh import shard_map

        d2, d1, _ = _row_specs(mesh)
        fn = shard_map(
            lambda Xl, nl: _blocked_xla(Xl, Y, mask, nl, "argmin_min2"),
            mesh=mesh, in_specs=(d2, d1),
            out_specs=(d1, d1, d1),
            check_vma=False)
        return fn(X, row_need)
    maskf = _maskf(mask, m)
    need2d = row_need.astype(jnp.float32)[None, :]
    if mesh is None:
        return _fused_pallas(X, Y, maskf, None, "argmin_min2",
                             need2d=need2d)
    from dask_ml_tpu.parallel.mesh import shard_map

    d2, d1, d1m = _row_specs(mesh)
    fn = shard_map(
        lambda Xl, Yl, ml, nl: _fused_pallas(Xl, Yl, ml, None,
                                             "argmin_min2", need2d=nl),
        mesh=mesh,
        in_specs=(d2, P(), P(), d1m),
        out_specs=(d1, d1, d1),
        check_vma=False)
    return fn(X, Y, maskf, need2d)


def fused_argmin_weight(X, w, Y, mask=None, *, kernel: str = "auto",
                        mesh=None):
    """Per-row argmin (int32, shape (n,)) plus the per-target weighted
    count ``cw[j] = Σ_i w_i · [argmin_i == j]`` (f32, shape (m,)) — the
    k-means|| candidate-weighting / M-step-count contraction, fused so
    neither the (n × m) distance matrix nor the (n × m) one-hot ever
    reaches HBM. Masked rows always get ``cw == 0``.

    The ``cw`` accumulation is the family's one cross-shard reduction; on
    a hierarchical mesh it lowers chip-then-pod through
    :func:`~dask_ml_tpu.parallel.hierarchy.hpsum` (ledger op
    ``fused.argmin_weight``) — on the XLA path too, which wraps in
    ``shard_map`` there (a flat mesh keeps today's plain GSPMD
    expression, bit-identical)."""
    from dask_ml_tpu.parallel.mesh import is_hierarchical, shard_map

    m, d = Y.shape
    if not _use_pallas(kernel, X.shape[0], m, d, X.dtype, mesh):
        if mesh is None or not is_hierarchical(mesh):
            if mesh is not None:
                # the flat XLA lowering's (m,) cw reduction is
                # GSPMD-implicit; record it so flat-vs-hierarchical
                # per-op accounting covers the same reduction regardless
                # of which kernel auto-selection wins (the same rule as
                # _tsqr_impl's flat Gram branch)
                from dask_ml_tpu.parallel.hierarchy import \
                    record_collective
                record_collective("fused.argmin_weight", mesh, (m,),
                                  jnp.float32)
            return _argmin_weight_ref(X, w, Y, mask)
        from dask_ml_tpu.parallel.hierarchy import hpsum

        d2, d1, _ = _row_specs(mesh)

        def local_xla(Xl, wl):
            s = _scores_ref(Xl, Y, mask)
            idx = jnp.argmin(s, axis=1).astype(jnp.int32)
            onehot = (jnp.arange(Y.shape[0], dtype=jnp.int32)[None, :]
                      == idx[:, None])
            cw = jax.lax.dot_general(
                wl.astype(jnp.float32), onehot.astype(jnp.float32),
                (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)  # (m,) local partial
            cw = hpsum(cw, mesh, op="fused.argmin_weight")
            if mask is not None:
                cw = jnp.where(mask, cw, 0.0)
            return idx, cw

        fn = shard_map(local_xla, mesh=mesh, in_specs=(d2, d1),
                       out_specs=(d1, P()), check_vma=False)
        return fn(X, w)
    maskf = _maskf(mask, m)
    w2d = w.astype(jnp.float32)[None, :]
    if mesh is None:
        return _fused_pallas(X, Y, maskf, w2d, "argmin_weight")
    from dask_ml_tpu.parallel.hierarchy import hpsum

    d2, d1, d1m = _row_specs(mesh)

    def local(Xl, wl, Yl, ml):
        am, cw = _fused_pallas(Xl, Yl, ml, wl, "argmin_weight")
        return am, hpsum(cw, mesh, op="fused.argmin_weight")

    fn = shard_map(
        local, mesh=mesh,
        in_specs=(d2, d1m, P(), P()),
        out_specs=(d1, P()), check_vma=False)
    return fn(X, w2d, Y, maskf)
