"""Sparse row-matrix kernels: blocked-ELL SpMM + segment-sum contractions.

The production workloads this package exists for (CTR, text, recommender
features at d >> 1e5) are >99% sparse; a dense (n, d) staging of them is not
slow but IMPOSSIBLE (4 TB for the 1e7 x 1e5 bench problem). This module is
the kernel tier of the sparse execution path (docs/sparse.md):

- :class:`SparseRows` — the device-side container: a row matrix in
  **blocked-ELL** layout, ``values (n, k)`` / ``cols (n, k)`` with ``k`` the
  per-row nonzero budget padded to a power-of-two bucket
  (:func:`dask_ml_tpu.parallel.shapes.bucket_nnz`). Both leaves shard
  ``P('data', None)`` exactly like a dense row matrix, so every consumer of
  the sharded layout (plain-jit GSPMD solvers, the shard_map ADMM, the
  streamed tier) takes the container with NO index re-basing: the layout is
  positional — row ``i`` of a shard's slice is row ``i`` of that shard.
  Registered as a pytree, so it passes through ``jit``/``vmap``/``scan``/
  ``shard_map``/``device_put`` untouched; the compile cache keys on the
  padded ``(rows, k)`` bucket plus ``d``, which is the compile-once
  discipline of docs/compile.md extended to sparse shapes.
- The two contractions every GLM solver routes through its seams
  (``models/glm.py::_data_matvec`` / ``_data_pullback``), plus the weighted
  Gram (``_weighted_gram``), each in an **XLA reference path** built from
  gather + row reduction / ``jax.ops.segment_sum`` scatter-add (runs
  everywhere, including CPU CI, and autodiffs natively) and — for the
  matvec/matmat — a **Pallas blocked-ELL SpMM** (:func:`spmv`) with f32
  accumulation and a custom VJP whose backward pass IS the segment-sum
  pullback, honoring the mixed-precision policy of docs/precision.md
  (operands feed the MXU in the values' wire dtype, accumulation >= f32).
- Per-trace collective metering (:func:`metered`): inside a metered scope
  the cross-shard contractions (pullback's (d,) reduction, the Gram's
  (d, d) reduction) record their analytic combining bytes into the
  hierarchy ledger (docs/scale-out.md) AT TRACE TIME — a jit cache hit
  records nothing, so zero steady-state compiles still implies zero ledger
  growth, exactly the per-trace semantics of ``parallel/hierarchy.py``.

Precision convention (mirrors :func:`dask_ml_tpu.parallel.precision.pdot`):
products are formed in the VALUES' dtype (bf16-staged values pull the dense
operand down to bf16), every reduction accumulates in the state dtype
(>= f32). On f32 data the reference kernels sum exactly the stored nonzeros
— on integer-valued data this is bit-identical to the dense matmul they
replace (every partial sum is an exactly-representable integer), which is
what the sparse-vs-dense exactness pins in ``tests/test_sparse.py`` assert.
"""

from __future__ import annotations

import contextlib
import threading

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

__all__ = [
    "SparseRows",
    "ell_from_csr",
    "ell_from_dense",
    "to_dense",
    "add_intercept_ell",
    "split_cols",
    "merge_cols",
    "matvec",
    "matmat",
    "pullback",
    "pullback_mat",
    "weighted_gram",
    "column_moments",
    "column_mean_var",
    "scale_columns",
    "spmv",
    "metered",
]


@jax.tree_util.register_pytree_node_class
class SparseRows:
    """A sparse (n, d) row matrix in blocked-ELL layout.

    ``values`` and ``cols`` are ``(n, k)``: row ``i`` holds its nonzeros in
    slots ``0..k-1`` (column index + value), with unused slots padded as
    ``(col=0, value=0)`` — inert in every contraction because the VALUE is
    zero, so no validity mask is ever needed. ``d`` (the true feature
    count) is static pytree aux data: it keys the compile cache together
    with the padded ``(n, k)`` leaf shapes, never the true ``nnz``.

    Duplicate column indices within a row are legal and SUM — the same
    linear-map semantics as a scipy matrix with duplicate entries.

    The container deliberately quacks like a 2-D array where the solver
    seams need it to (``shape``/``ndim``/``dtype``/``nbytes``), so the GLM
    cores dispatch on type at the three X-touching seams and change
    nothing else.
    """

    def __init__(self, values, cols, d: int):
        self.values = values
        self.cols = cols
        self.d = int(d)

    def tree_flatten(self):
        return (self.values, self.cols), (self.d,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        obj = object.__new__(cls)
        obj.values, obj.cols = children
        obj.d = aux[0]
        return obj

    # -- array-like surface (what the solver seams read) -------------------

    @property
    def shape(self) -> tuple:
        return (self.values.shape[0], self.d)

    @property
    def ndim(self) -> int:
        return 2

    @property
    def dtype(self):
        return self.values.dtype

    @property
    def k(self) -> int:
        """The per-row nonzero budget (the padded ELL width)."""
        return int(self.values.shape[1])

    @property
    def nbytes(self) -> int:
        """ACTUAL bytes held (values + indices) — the nnz-based size
        ``utils/_log.py::log_array`` reports, not the dense n*d*itemsize."""
        return int(self.values.nbytes) + int(self.cols.nbytes)

    @property
    def sharding(self):
        """Placement of the container = placement of its values leaf (both
        leaves are staged identically)."""
        return getattr(self.values, "sharding", None)

    def astype(self, dtype):
        return SparseRows(self.values.astype(dtype), self.cols, self.d)

    def __getitem__(self, idx):
        """Row slicing/gathering (CV-style use: slices and index arrays);
        for column ranges use :func:`split_cols` (the indices are
        positional, so ``[]``-style column slicing has no cheap meaning —
        a range split re-bases every slot). Scalar indices are rejected —
        they would drop the row axis and leave a container whose
        shape/ndim lie."""
        if isinstance(idx, (int, np.integer)):
            raise TypeError(
                "SparseRows rows are indexed with slices or index arrays "
                f"(got scalar {idx!r}); use A[i:i+1] to keep the row axis")
        return SparseRows(self.values[idx], self.cols[idx], self.d)

    def __repr__(self):
        return (f"SparseRows(shape={self.shape}, k={self.values.shape[1]}, "
                f"dtype={self.dtype})")


def is_sparse_rows(x) -> bool:
    return isinstance(x, SparseRows)


# ---------------------------------------------------------------------------
# host-side encoding (numpy; the wire format the streamed tier moves)
# ---------------------------------------------------------------------------


def ell_from_csr(X, k: int = None, dtype=None) -> SparseRows:
    """Encode a scipy CSR/CSC/COO matrix as a host-array :class:`SparseRows`.

    ``k`` (default: :func:`~dask_ml_tpu.parallel.shapes.bucket_nnz` of the
    max row nonzero count) is the per-row slot budget — pass it explicitly
    to pin several blocks of one dataset to a COMMON width (the streamed
    tier does; unequal widths would compile one program per block).
    Vectorized fill: O(nnz) host work, no per-row Python loop.
    """
    import scipy.sparse

    from dask_ml_tpu.parallel import shapes

    if not scipy.sparse.issparse(X):
        raise TypeError(f"ell_from_csr expects a scipy sparse matrix, got "
                        f"{type(X).__name__}")
    X = X.tocsr()
    n, d = X.shape
    row_nnz = np.diff(X.indptr)
    k_true = int(row_nnz.max()) if n else 0
    if k is None:
        k = shapes.bucket_nnz(k_true)
    elif k_true > int(k):
        raise ValueError(
            f"a row has {k_true} nonzeros, more than the requested ELL "
            f"width k={k}; widen k (blocks of one dataset must share the "
            "max row-nnz bucket)")
    k = max(int(k), 1)
    vdt = np.dtype(dtype) if dtype is not None else (
        X.dtype if np.issubdtype(X.dtype, np.floating) else np.float32)
    values = np.zeros((n, k), vdt)
    cols = np.zeros((n, k), np.int32)
    if X.nnz:
        r = np.repeat(np.arange(n), row_nnz)
        slot = np.arange(X.nnz) - np.repeat(X.indptr[:-1], row_nnz)
        values[r, slot] = X.data.astype(vdt, copy=False)
        cols[r, slot] = X.indices.astype(np.int32, copy=False)
    return SparseRows(values, cols, d)


def ell_from_dense(X, k: int = None, dtype=None) -> SparseRows:
    """Encode a dense host array (test/bench convenience)."""
    import scipy.sparse

    return ell_from_csr(scipy.sparse.csr_matrix(np.asarray(X)), k=k,
                        dtype=dtype)


def to_dense(A: SparseRows):
    """Densify (small sizes / tests): duplicate column slots SUM."""
    n, k = A.values.shape
    rows = jnp.broadcast_to(jnp.arange(n)[:, None], (n, k))
    out = jnp.zeros((n, A.d), _accum_dtype(A))
    return out.at[rows, A.cols].add(A.values.astype(out.dtype))


def add_intercept_ell(A: SparseRows) -> SparseRows:
    """Append an intercept column (all-ones, column index ``d``) as ONE
    extra slot per row — the sparse analogue of the dense ones-column
    append, device-side and jit-traceable so it fuses into the consuming
    program exactly like ``linear_model.glm.add_intercept`` does."""
    n = A.values.shape[0]
    xp = np if isinstance(A.values, np.ndarray) else jnp
    ones = xp.ones((n, 1), A.values.dtype)
    icol = xp.full((n, 1), A.d, dtype=A.cols.dtype)
    return SparseRows(xp.concatenate([A.values, ones], axis=1),
                      xp.concatenate([A.cols, icol], axis=1), A.d + 1)


def split_cols(A: SparseRows, edges) -> list:
    """Split the FEATURE axis into contiguous column ranges — the column
    split the blocked-ELL layout composes with (row sharding stays
    ``P('data', None)`` per block; the feature-parallel tier assigns one
    block per model shard).

    ``edges`` are the interior split points (``np.split`` convention):
    ``split_cols(A, [4, 9])`` on ``d=12`` yields blocks over columns
    ``[0, 4)``, ``[4, 9)``, ``[9, 12)``. Each block keeps the full slot
    budget ``k``: slots whose column falls outside the block's range are
    blanked to the inert ``(col=0, value=0)`` encoding, and in-range
    columns re-base to the block's origin (``col - lo``), so every block
    is a self-contained :class:`SparseRows` of width ``hi - lo``.

    Semantics: ``matvec(A, v) == sum_j matvec(B_j, v[lo_j:hi_j])``,
    pullbacks concatenate, and ``weighted_gram(B_j, h)`` is the j-th
    DIAGONAL block of the full Gram (cross-block terms need the dense
    path). Exact — blanking moves only value-0 products.

    Caveat: blanked slots all alias column 0, so a split block generally
    fails :func:`has_duplicate_slots`' no-duplicates precondition only in
    appearance — the duplicates are value-0 and the LINEAR contractions
    remain exact; the quadratic moment reductions mask on ``value != 0``
    and are likewise unaffected. Works on host (numpy) and device arrays.
    """
    edges = [int(e) for e in edges]
    bounds = [0, *edges, A.d]
    if any(b1 > b2 for b1, b2 in zip(bounds, bounds[1:])) \
            or (edges and (edges[0] < 0 or edges[-1] > A.d)):
        raise ValueError(
            f"split edges {edges} must be nondecreasing within [0, {A.d}]")
    xp = np if isinstance(A.values, np.ndarray) else jnp
    blocks = []
    for lo, hi in zip(bounds, bounds[1:]):
        inr = (A.cols >= lo) & (A.cols < hi) & (A.values != 0)
        vals = xp.where(inr, A.values, xp.zeros_like(A.values))
        cols = xp.where(inr, A.cols - lo, xp.zeros_like(A.cols))
        blocks.append(SparseRows(vals, cols.astype(A.cols.dtype), hi - lo))
    return blocks


def merge_cols(blocks) -> SparseRows:
    """Invert :func:`split_cols`: concatenate column-range blocks back into
    one container over the summed width. Blocks stack along the SLOT axis
    (each block's slots re-base by its running column offset), so the
    merged ``k`` is the sum of the blocks' — round-trip equality is up to
    slot layout, not bit-layout: ``to_dense(merge_cols(split_cols(A, e)))
    == to_dense(A)`` exactly, while the slot arrangement differs."""
    if not blocks:
        raise ValueError("merge_cols needs at least one block")
    n = blocks[0].values.shape[0]
    if any(b.values.shape[0] != n for b in blocks):
        raise ValueError("blocks must share the row count")
    xp = np if isinstance(blocks[0].values, np.ndarray) else jnp
    vals, cols, off = [], [], 0
    for b in blocks:
        stored = b.values != 0
        vals.append(b.values)
        cols.append(xp.where(stored, b.cols + off,
                             xp.zeros_like(b.cols)))
        off += b.d
    return SparseRows(xp.concatenate(vals, axis=1),
                      xp.concatenate(cols, axis=1).astype(blocks[0].cols.dtype),
                      off)


# ---------------------------------------------------------------------------
# per-trace collective metering (the hierarchy ledger hook)
# ---------------------------------------------------------------------------

_METER = threading.local()


@contextlib.contextmanager
def metered(mesh):
    """Scope within which the cross-shard sparse contractions (pullback,
    weighted Gram) record their analytic combining bytes into the traffic
    ledger under ops ``sparse.pullback`` / ``sparse.gram``. Recording
    happens inside the TRACED helpers, i.e. once per trace — a compile
    cache hit records nothing (the per-trace semantics of
    ``parallel/hierarchy.py``, which is what lets the bench pin
    zero-steady-state-compiles as zero ledger growth). The facades enter
    this scope around solver dispatch when the staged data is sparse."""
    prev = getattr(_METER, "mesh", None)
    _METER.mesh = mesh
    try:
        yield
    finally:
        _METER.mesh = prev


def _record(op: str, shape, dtype) -> None:
    mesh = getattr(_METER, "mesh", None)
    if mesh is None:
        return
    from dask_ml_tpu.parallel.hierarchy import record_collective

    record_collective(op, mesh, shape, dtype)


# ---------------------------------------------------------------------------
# the contractions (XLA reference path)
# ---------------------------------------------------------------------------


def _accum_dtype(A: SparseRows):
    from dask_ml_tpu.parallel import precision as px

    return px.state_dtype(A.dtype)


def matvec(A: SparseRows, v, *, kernel: str = "auto"):
    """``A @ v`` — the sparse linear predictor. ``v`` is ``(d,)`` (or the
    operand's true width; callers pass coefficient vectors sized to
    ``A.d``). Products form in the values' (possibly bf16) dtype, the
    per-row reduction accumulates >= f32 — the same discipline as
    :func:`~dask_ml_tpu.parallel.precision.pmatmul` on dense rows.

    ``kernel='auto'`` uses the Pallas blocked-ELL SpMM on TPU (when the
    row count tiles) and the XLA gather+rowsum reference elsewhere;
    ``'xla'``/``'pallas'`` force a path (pallas runs in interpret mode off
    TPU — slow, CI-only). Purely rowwise: shards under GSPMD with no
    collective, and autodiff w.r.t. ``v`` yields exactly the segment-sum
    pullback."""
    if _use_pallas(A, kernel):
        return spmv(A, v)
    cd = A.dtype
    acc = _accum_dtype(A)
    prods = A.values * v.astype(cd)[A.cols]
    return jnp.sum(prods.astype(acc), axis=1)


def matmat(A: SparseRows, B):
    """``A @ B`` for a dense ``(d, m)`` operand (multinomial logits,
    batched-coefficient scoring): gather ``B``'s rows per slot, reduce over
    slots with f32 accumulation. Memory is O(n * k * m) transient — fine
    for the small ``m`` (class counts, candidate counts) it serves."""
    cd = A.dtype
    acc = _accum_dtype(A)
    g = B.astype(cd)[A.cols]                    # (n, k, m)
    prods = A.values[:, :, None] * g
    return jnp.sum(prods.astype(acc), axis=1)   # (n, m)


def pullback(A: SparseRows, r):
    """``A.T @ r`` — the gradient pullback, as a ``segment_sum``
    scatter-add over the flattened column indices (f32 accumulation;
    padded slots carry value 0 and contribute nothing wherever their
    column index points). The one sparse contraction whose output reduces
    ACROSS shards: inside a :func:`metered` scope it records the analytic
    (n_shards-1) * d * 4 combining bytes per trace as ``sparse.pullback``."""
    cd = A.dtype
    acc = _accum_dtype(A)
    _record("sparse.pullback", (A.d,), acc)
    prods = (A.values * r.astype(cd)[:, None]).astype(acc)
    return jax.ops.segment_sum(prods.ravel(), A.cols.ravel(),
                               num_segments=A.d)


def pullback_mat(A: SparseRows, R):
    """``A.T @ R`` for a dense ``(n, m)`` cotangent (multinomial
    gradients): segment-sum over columns, vectorized over ``m``."""
    cd = A.dtype
    acc = _accum_dtype(A)
    _record("sparse.pullback", (A.d, int(R.shape[1])), acc)
    n, k = A.values.shape
    prods = (A.values[:, :, None] * R.astype(cd)[:, None, :]).astype(acc)
    return jax.ops.segment_sum(prods.reshape(n * k, -1), A.cols.ravel(),
                               num_segments=A.d)


def _gram_chunk(n: int, k: int, budget: int = 1 << 22) -> int:
    """Largest row-chunk size dividing ``n`` with chunk*k*k <= budget —
    static (host) arithmetic bounding the transient (chunk, k, k) outer-
    product buffer of :func:`weighted_gram`. Bounded search: a short
    downward scan for a divisor, then the largest power of two dividing
    ``n`` (staged row counts are bucketed and even; a pathological prime
    ``n`` degrades to more scan steps, never to a host-side spin)."""
    if n == 0:
        return 1
    cap = max(1, min(n, budget // max(k * k, 1)))
    for c in range(cap, max(cap - 64, 0), -1):
        if n % c == 0:
            return c
    p2 = n & -n  # largest power of two dividing n
    while p2 > cap:
        p2 //= 2
    return max(p2, 1)


def weighted_gram(A: SparseRows, h):
    """``A.T @ diag(h) @ A`` — the (d, d) GLM curvature, as a chunked
    scatter-add of per-row outer products over each row's <= k*k nonzero
    pairs (O(nnz * k) work instead of the dense O(n * d^2); transient
    memory bounded by :func:`_gram_chunk`). Accumulates f32. Only
    meaningful where a dense (d, d) Hessian is meaningful at all (Newton /
    ADMM inner solves at moderate d); the wide-d sparse regime runs the
    gradient-only solvers, which never touch this."""
    acc = _accum_dtype(A)
    _record("sparse.gram", (A.d, A.d), acc)
    n, k = A.values.shape
    w = (A.values.astype(acc) * h.astype(acc)[:, None])     # (n, k)
    vals = A.values.astype(acc)
    c = _gram_chunk(n, k)
    wc = w.reshape(n // c, c, k)
    vc = vals.reshape(n // c, c, k)
    cc = A.cols.reshape(n // c, c, k)

    def body(H, inp):
        wv, vv, ci = inp
        contrib = wv[:, :, None] * vv[:, None, :]           # (c, k, k)
        return H.at[ci[:, :, None], ci[:, None, :]].add(contrib), None

    H, _ = lax.scan(body, jnp.zeros((A.d, A.d), acc), (wc, vc, cc))
    return H


# ---------------------------------------------------------------------------
# Pallas blocked-ELL SpMM
# ---------------------------------------------------------------------------

#: rows per grid step of the Pallas kernel — one (R, k) values/cols tile
#: plus the replicated operand vector resident in VMEM per step
_SPMV_BLK = 256


def _use_pallas(A: SparseRows, kernel: str) -> bool:
    if kernel == "xla":
        return False
    n = int(A.values.shape[0])
    tiles = n >= 1 and n % min(n, _SPMV_BLK) == 0
    if kernel == "pallas":
        if not tiles:
            raise ValueError(
                f"pallas spmv needs the row count ({n}) to tile by "
                f"{min(n, _SPMV_BLK)}; stage through the bucketing layer "
                "or use kernel='xla'")
        return True
    if kernel != "auto":
        raise ValueError(f"kernel must be 'auto', 'xla' or 'pallas', "
                         f"got {kernel!r}")
    if not tiles:
        return False  # correctness guard: never a cache question
    # auto: measured decision-cache verdict where the bench has timed this
    # regime (parallel/decisions.py), else the hand-written fallback — the
    # hand-scheduled path only where it can win: on TPU, with tiling row
    # counts (every bucketed staging tiles). Off-TPU pallas only
    # interprets (CI correctness, not speed).
    from dask_ml_tpu.parallel import decisions

    return decisions.lookup(
        "sparse.spmv.pallas",
        {"n": n, "k": int(A.values.shape[1]), "dtype": str(A.dtype)},
        fallback=jax.default_backend() == "tpu")


@jax.custom_vjp
def spmv(A: SparseRows, v):
    """Blocked-ELL SpMM ``A @ v`` as a Pallas kernel: the grid walks
    (R, k) row tiles; each step gathers the operand entries its tile's
    column indices name from the VMEM-resident ``v`` and reduces the
    products in f32 — the epilogue never leaves VMEM (the
    ``ops/fused_distance.py`` family's discipline). Off-TPU the kernel
    runs in interpret mode (CPU CI). The custom VJP's backward pass is the
    segment-sum :func:`pullback` (w.r.t. ``v``) and the slot-wise gather
    product (w.r.t. ``values``), so the Pallas path is usable inside
    differentiated objectives with gradients identical to the XLA
    reference path."""
    return _spmv_impl(A, v)


def _spmv_impl(A: SparseRows, v):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_true, k = A.values.shape
    blk = min(n_true, _SPMV_BLK)
    pad = (-n_true) % max(blk, 1)
    if pad:
        # non-tiling row counts pad up to the grid (value-0 slots are
        # inert) and slice back — the public entry point must be correct
        # for EVERY n, not only the bucketed sizes the auto path admits
        A = SparseRows(jnp.pad(A.values, [(0, pad), (0, 0)]),
                       jnp.pad(A.cols, [(0, pad), (0, 0)]), A.d)
    n, k = A.values.shape
    acc = _accum_dtype(A)
    v2 = v.astype(A.dtype).reshape(-1, 1)
    d_op = int(v2.shape[0])

    def kern(val_ref, col_ref, v_ref, out_ref):
        vals = val_ref[:]                       # (blk, k)
        cidx = col_ref[:]                       # (blk, k)
        g = v_ref[:, 0][cidx]                   # gather (blk, k)
        prods = (vals.astype(acc) * g.astype(acc))
        out_ref[:] = jnp.sum(prods, axis=1, keepdims=True)

    out = pl.pallas_call(
        kern,
        grid=(n // blk,),
        in_specs=[
            pl.BlockSpec((blk, k), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((blk, k), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((d_op, 1), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((blk, 1), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n, 1), acc),
        interpret=jax.default_backend() != "tpu",
    )(A.values, A.cols, v2)
    return out[:n_true, 0]


def _spmv_fwd(A, v):
    return _spmv_impl(A, v), (A, v)


def _spmv_bwd(res, g):
    A, v = res
    dvalues = (g.astype(A.dtype)[:, None] * v.astype(A.dtype)[A.cols])
    dcols = np.zeros(A.cols.shape, dtype=jax.dtypes.float0)
    dv = pullback(A, g).astype(v.dtype)
    return SparseRows(dvalues, dcols, A.d), dv


spmv.defvjp(_spmv_fwd, _spmv_bwd)


# ---------------------------------------------------------------------------
# column moments (the sparse StandardScaler reduction)
# ---------------------------------------------------------------------------


@jax.jit
def column_moments(A: SparseRows, w):
    """Weighted per-column first/second moments from the NONZEROS only:
    ``(sum_i w_i x_ij, sum_i w_i x_ij^2, sum_i w_i)`` in O(nnz) (zeros
    contribute nothing to either sum). f32 scatter accumulation; padding
    rows carry weight 0 like everywhere else. Like
    :func:`column_mean_var`, the quadratic sum assumes at most one stored
    entry per (row, column): duplicate slots contribute ``v1^2 + v2^2``
    where the summed-duplicate semantics would need ``(v1 + v2)^2``
    (canonical CSR — every scipy input — has no duplicates)."""
    acc = _accum_dtype(A)
    vals = A.values.astype(acc)
    wv = w.astype(acc)[:, None]
    flat_cols = A.cols.ravel()
    s1 = jax.ops.segment_sum((wv * vals).ravel(), flat_cols,
                             num_segments=A.d)
    s2 = jax.ops.segment_sum((wv * vals * vals).ravel(), flat_cols,
                             num_segments=A.d)
    return s1, s2, jnp.sum(w.astype(acc))


@jax.jit
def column_mean_var(A: SparseRows, w):
    """Weighted per-column ``(mean, var, sum_w)`` by the numerically
    stable TWO-PASS form — the sparse ``StandardScaler`` reduction.

    The one-pass ``E[x^2] - mean^2`` identity cancels catastrophically in
    f32 for columns whose mean dwarfs their spread (count/offset features:
    mean ~1e3, var ~1 → both terms ~1e6, difference below f32 resolution).
    Here pass 1 takes the mean, pass 2 sums ``w·(x - mean)^2`` over the
    stored entries PLUS the closed-form zero contribution
    ``(sum_w - nnz_w_j)·mean_j^2`` (``nnz_w_j`` = weighted count of stored
    entries in column j, masked on ``value != 0`` so padded slots and
    explicit stored zeros both land in the zero term). Still O(nnz), two
    passes. Assumes at most one stored entry per (row, column) — the
    canonical-CSR case; duplicate slots are supported by the LINEAR
    contractions but not by quadratic moments."""
    acc = _accum_dtype(A)
    vals = A.values.astype(acc)
    wv = w.astype(acc)[:, None]
    flat_cols = A.cols.ravel()
    sw = jnp.sum(w.astype(acc))
    s1 = jax.ops.segment_sum((wv * vals).ravel(), flat_cols,
                             num_segments=A.d)
    denom = jnp.maximum(sw, 1.0)
    mean = s1 / denom
    stored = (vals != 0).astype(acc)
    nnz_w = jax.ops.segment_sum((wv * stored).ravel(), flat_cols,
                                num_segments=A.d)
    dev2 = jax.ops.segment_sum(
        (wv * stored * (vals - mean[A.cols]) ** 2).ravel(), flat_cols,
        num_segments=A.d)
    var = (dev2 + (sw - nnz_w) * mean * mean) / denom
    return mean, jnp.maximum(var, 0.0), sw


@jax.jit
def has_duplicate_slots(A: SparseRows):
    """True if any row stores the SAME column index in two nonzero slots.
    The linear contractions sum duplicates correctly (scipy semantics),
    but the QUADRATIC moment reductions (:func:`column_moments` /
    :func:`column_mean_var`) cannot be computed slot-wise over them —
    the sparse ``StandardScaler`` uses this O(nnz log k) device check to
    reject such containers loudly instead of returning silently wrong
    variances. Unstored (value-0) slots never count as duplicates."""
    n, k = A.values.shape
    # stored slots keep their column id; unstored slots get a unique
    # per-slot negative sentinel so they can never collide
    sentinel = -1 - jnp.arange(k, dtype=A.cols.dtype)[None, :]
    c = jnp.where(A.values != 0, A.cols, sentinel)
    c = jnp.sort(c, axis=1)
    if k < 2:
        return jnp.asarray(False)
    return jnp.any(c[:, 1:] == c[:, :-1])


@jax.jit
def scale_columns(A: SparseRows, scale):
    """Divide each nonzero by its column's scale factor (the sparse
    ``StandardScaler.transform``): a pure gather + elementwise multiply —
    the container's layout (and therefore its compiled-program bucket) is
    unchanged."""
    inv = (1.0 / scale).astype(_accum_dtype(A))
    out = (A.values.astype(inv.dtype) * inv[A.cols]).astype(A.dtype)
    return SparseRows(out, A.cols, A.d)
