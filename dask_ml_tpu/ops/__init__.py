"""Compute primitives: pairwise kernels, the fused distance-reduction
kernel family (``ops.fused_distance`` — see docs/kernels.md), distributed
linear algebra, segment reductions. The TPU-native replacement for the
reference's L3 primitives layer (reference: dask_ml/metrics/pairwise.py,
the Cython ``_k_means.pyx`` kernel, and the ``da.linalg`` routines it
borrows)."""
