"""Compute primitives: pairwise kernels, distributed linear algebra,
segment reductions. The TPU-native replacement for the reference's L3
primitives layer (reference: dask_ml/metrics/pairwise.py, the Cython
``_k_means.pyx`` kernel, and the ``da.linalg`` routines it borrows)."""
