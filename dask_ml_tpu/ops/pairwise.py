"""Pairwise distances and kernels as fused XLA matmuls.

The reference computes distances per block by calling sklearn's Cython kernels
inside delayed tasks (reference: metrics/pairwise.py:20-50) and restricts ``Y``
to an in-memory NumPy array (reference: metrics/pairwise.py:53-59 — centers are
replicated into every task). The TPU-native version keeps the same contract —
``X`` is sample-axis sharded, ``Y`` is small and replicated — but the whole
computation is one jitted ``|x|² + |y|² − 2·X@Yᵀ`` expression: the X@Yᵀ
term
lands on the MXU and XLA fuses the norm/clamp/argmin epilogue, so
assignment-style ops never materialize more than an (n_shard × k) block
per device.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def check_pairwise_arrays(X, Y, precomputed: bool = False):
    """Validate/align a pair of operands for a pairwise op
    (reference: metrics/pairwise.py:53-59, which wraps sklearn's checker
    per-block). Returns ``(X, Y)`` as float arrays with ``Y = X`` when None;
    raises on feature-dimension mismatch (or, for ``precomputed=True``, when
    ``X.shape[1] != Y.shape[0]``)."""
    X = jnp.asarray(X)
    if X.ndim != 2:
        raise ValueError(
            f"Expected a 2-D array for X, got {X.ndim}-D shape {X.shape}"
        )
    X = X.astype(jnp.float32) if not jnp.issubdtype(X.dtype, jnp.floating) \
        else X
    if Y is None:
        Y = X
    else:
        Y = jnp.asarray(Y)
        if Y.ndim != 2:
            raise ValueError(
                f"Expected a 2-D array for Y, got {Y.ndim}-D shape {Y.shape}"
            )
        Y = Y.astype(jnp.float32) \
            if not jnp.issubdtype(Y.dtype, jnp.floating) else Y
    if precomputed:
        if X.shape[1] != Y.shape[0]:
            raise ValueError(
                "Precomputed metric requires shape (n_queries, n_indexed). "
                f"Got ({X.shape[0]}, {X.shape[1]}) for {Y.shape[0]} indexed."
            )
    elif X.shape[1] != Y.shape[1]:
        raise ValueError(
            "Incompatible dimension for X and Y matrices: "
            f"X.shape[1] == {X.shape[1]} while Y.shape[1] == {Y.shape[1]}"
        )
    return X, Y


@jax.jit
def sq_euclidean(X: jax.Array, Y: jax.Array) -> jax.Array:
    """Squared Euclidean distance matrix, clamped at 0 against cancellation
    (same guard as reference: metrics/pairwise.py:62-91)."""
    x2 = jnp.sum(X * X, axis=1)[:, None]
    y2 = jnp.sum(Y * Y, axis=1)[None, :]
    d2 = x2 + y2 - 2.0 * (X @ Y.T)
    return jnp.maximum(d2, 0.0)


@jax.jit
def euclidean_distances(X: jax.Array, Y: jax.Array | None = None) -> jax.Array:
    if Y is None:
        # X-vs-X: force an exactly-zero diagonal; the ‖x‖²+‖y‖²−2x·y form
        # leaves ~1e-3 of f32 cancellation error there (sklearn does the same
        # zeroing in its euclidean_distances). Iota comparison fuses into the
        # epilogue without materializing an n×n identity.
        d2 = sq_euclidean(X, X)
        n = d2.shape[0]
        rows = jax.lax.broadcasted_iota(jnp.int32, (n, n), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (n, n), 1)
        d2 = jnp.where(rows == cols, 0.0, d2)
        return jnp.sqrt(d2)
    return jnp.sqrt(sq_euclidean(X, Y))


@partial(jax.jit, static_argnames=("kernel", "mesh"))
def pairwise_distances_argmin_min(
    X: jax.Array, Y: jax.Array, *, kernel: str = "auto", mesh=None
) -> tuple[jax.Array, jax.Array]:
    """For each row of X, the index of and distance to the nearest row of Y
    (reference: metrics/pairwise.py:20-50). Routed through the fused
    distance-reduction family (:mod:`dask_ml_tpu.ops.fused_distance`):
    ``kernel='auto'`` (default) picks the tiled single-pass Pallas kernel
    in its measured winning regimes and the XLA-lowered expression
    elsewhere; no (n × m) matrix survives either epilogue on TPU, and the
    pallas path never even materializes it in HBM. Pass ``mesh`` for
    row-sharded X when forcing ``kernel='pallas'`` (see docs/kernels.md)."""
    from dask_ml_tpu.ops.fused_distance import fused_argmin_min

    argmin, mind = fused_argmin_min(X, Y, kernel=kernel, mesh=mesh)
    return argmin, jnp.sqrt(mind)


@jax.jit
def linear_kernel(X: jax.Array, Y: jax.Array | None = None) -> jax.Array:
    if Y is None:
        Y = X
    return X @ Y.T


@partial(jax.jit, static_argnames=("gamma",))
def rbf_kernel(
    X: jax.Array, Y: jax.Array | None = None, gamma: float | None = None
) -> jax.Array:
    if Y is None:
        Y = X
    if gamma is None:
        gamma = 1.0 / X.shape[1]
    return jnp.exp(-gamma * sq_euclidean(X, Y))


@partial(jax.jit, static_argnames=("degree", "gamma", "coef0"))
def polynomial_kernel(
    X: jax.Array,
    Y: jax.Array | None = None,
    degree: int = 3,
    gamma: float | None = None,
    coef0: float = 1.0,
) -> jax.Array:
    if Y is None:
        Y = X
    if gamma is None:
        gamma = 1.0 / X.shape[1]
    return (gamma * (X @ Y.T) + coef0) ** degree


@partial(jax.jit, static_argnames=("gamma", "coef0"))
def sigmoid_kernel(
    X: jax.Array,
    Y: jax.Array | None = None,
    gamma: float | None = None,
    coef0: float = 1.0,
) -> jax.Array:
    if Y is None:
        Y = X
    if gamma is None:
        gamma = 1.0 / X.shape[1]
    return jnp.tanh(gamma * (X @ Y.T) + coef0)


PAIRWISE_KERNEL_FUNCTIONS = {
    "linear": linear_kernel,
    "rbf": rbf_kernel,
    "polynomial": polynomial_kernel,
    "poly": polynomial_kernel,
    "sigmoid": sigmoid_kernel,
}

_KERNEL_PARAMS = {
    "linear": set(),
    "rbf": {"gamma"},
    "polynomial": {"degree", "gamma", "coef0"},
    "poly": {"degree", "gamma", "coef0"},
    "sigmoid": {"gamma", "coef0"},
}


def pairwise_kernels(X, Y=None, metric: str = "linear", **kwds):
    """Kernel registry dispatch (reference: metrics/pairwise.py:116-188).
    ``metric`` may also be a callable taking (X, Y)."""
    if callable(metric):
        return metric(X, X if Y is None else Y, **kwds)
    if metric not in PAIRWISE_KERNEL_FUNCTIONS:
        raise ValueError(
            f"Unknown kernel {metric!r}; valid: "
            f"{sorted(set(PAIRWISE_KERNEL_FUNCTIONS))}"
        )
    kwds = {k: v for k, v in kwds.items() if k in _KERNEL_PARAMS[metric]}
    return PAIRWISE_KERNEL_FUNCTIONS[metric](X, Y, **kwds)


def pairwise_distances(X, Y=None, metric: str = "euclidean", **kwds):
    """Distance registry (reference: metrics/pairwise.py:53-59). ``Y`` must be
    small/replicated, as in the reference."""
    if callable(metric):
        return metric(X, X if Y is None else Y, **kwds)
    if metric == "euclidean":
        return euclidean_distances(X, Y)
    if metric == "sqeuclidean":
        return sq_euclidean(X, X if Y is None else Y)
    raise ValueError(f"Unknown distance metric {metric!r}")
