"""Distributed tall-skinny linear algebra: tsqr, SVD, randomized SVD.

The reference gets all of this for free from ``da.linalg.svd`` /
``da.linalg.svd_compressed`` (reference: decomposition/pca.py:233-241,
truncated_svd.py:163-171); the survey assigns the implementation to this
build (SURVEY §7.2-4: "we own the tsqr"). TPU-native design:

- **tsqr** (Benson/Gleich/Demmel 2013, the algorithm the reference cites at
  pca.py:121-127): the DEFAULT path is CholeskyQR2 — two rounds of
  (sharded Gram matmul → replicated small Cholesky → triangular solve),
  every FLOP a matmul/trsm on the MXU — with a measured-orthogonality
  guard that falls back, inside the same XLA program (``lax.cond``), to
  the Householder variant: one ``shard_map`` program where each shard
  takes a local ``jnp.linalg.qr`` of its row block, the small R factors
  are gathered over the ICI (P·d×d total — tiny), every shard runs the
  same small stacked QR (replicated compute beats a scatter round-trip),
  and the local Q is patched with its slice of the small Q. The
  reference's recursive dask reduction tree collapses to one gather
  because mesh sizes (≤ thousands of chips) never need a multi-level tree
  for d×d blocks.
- **SVD via tsqr**: SVD of the small R, then ``U = Q @ U_r`` locally.
- **svd_compressed** (Halko/Martinsson/Tropp randomized range finder with QR
  power iterations — the ``da.linalg.svd_compressed`` analogue): sharded
  matmuls against a replicated test matrix; every cross-shard contraction is
  an automatic ``psum``.
- **svd_flip**: deterministic sign convention, jitted (reference delegates
  to sklearn via a delayed task, utils.py:18-25).

Padding rows are exact zeros (callers must center-then-mask, see
:meth:`dask_ml_tpu.decomposition.PCA`): a zero row contributes nothing to R
and gets an exactly-zero U row, so unpadding is a plain slice.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from dask_ml_tpu.parallel import hierarchy as hier
from dask_ml_tpu.parallel import mesh as mesh_lib
from dask_ml_tpu.parallel import precision as px
from dask_ml_tpu.parallel.mesh import CHIP_AXIS, DATA_AXIS, POD_AXIS


def _gather_axis(x, axis_name, n, mesh=None):
    """All-gather over ONE named mesh axis that produces a
    *replication-typed* (invariant-over-that-axis) result: scatter into a
    zero buffer + psum. all_gather's output is typed varying under
    shard_map's vma checks, which would block P() out_specs; psum's output
    is invariant by construction. The blocks here are tiny R factors, so
    the extra zeros on the wire are noise. ``mesh`` (when given) records
    the gather's logical bytes into the per-axis traffic ledger
    (parallel/hierarchy.py) — the tsqr tree's stacking traffic."""
    if mesh is not None:
        hier.record_axis_collective("tsqr.gather", mesh, axis_name,
                                    int(np.prod(x.shape)) * x.dtype.itemsize)
    idx = lax.axis_index(axis_name)
    buf = jnp.zeros((n,) + x.shape, x.dtype)
    buf = lax.dynamic_update_slice_in_dim(buf, x[None], idx, axis=0)
    buf = lax.psum(buf, axis_name)
    return buf.reshape((n * x.shape[0],) + x.shape[1:])


@partial(jax.jit, static_argnames=("mesh",))
def _tsqr_householder_impl(X, *, mesh):
    """Per-shard Householder QR + gathered small QR — the numerically
    bulletproof (but MXU-unfriendly: sequential panel factorizations) path.
    Kept as the fallback branch of :func:`_tsqr_impl`'s condition guard.

    On a hierarchical ``('pod', 'chip')`` mesh the reduction tree gets a
    REAL middle level (the Benson/Gleich/Demmel tree the flat path
    collapses): local QR → within-pod gather + stacked QR over the ICI →
    cross-pod gather + stacked QR over the DCN, so only one pod-level
    ``(k, d)`` factor per pod crosses the DCN instead of every shard's —
    the communication-avoiding structure, with both gather stages metered
    per axis in the traffic ledger. Q back-propagates through both small
    Q slices (``Q = Q1 @ (Q2_i @ Q3_p)``)."""
    if mesh_lib.is_hierarchical(mesh):
        n_pods = mesh.shape[POD_AXIS]
        cpp = mesh.shape[CHIP_AXIS]

        @partial(
            mesh_lib.shard_map,
            mesh=mesh,
            in_specs=mesh_lib.data_pspec(mesh),
            out_specs=(mesh_lib.data_pspec(mesh), P()),
        )
        def run_hier(X_loc):
            n_loc, d = X_loc.shape
            k1 = min(n_loc, d)
            Q1, R1 = jnp.linalg.qr(X_loc, mode="reduced")
            # level 1: stack the pod's chip factors over the ICI
            Rs_pod = _gather_axis(R1, CHIP_AXIS, cpp, mesh=mesh)
            Q2, R2 = jnp.linalg.qr(Rs_pod, mode="reduced")  # (cpp·k1, k2)
            k2 = min(cpp * k1, d)
            ci = lax.axis_index(CHIP_AXIS)
            Q2_i = lax.dynamic_slice_in_dim(Q2, ci * k1, k1, axis=0)
            # level 2: one reduced (k2, d) factor per pod crosses the DCN
            Rs_all = _gather_axis(R2, POD_AXIS, n_pods, mesh=mesh)
            Q3, R = jnp.linalg.qr(Rs_all, mode="reduced")  # (pods·k2, k3)
            pi = lax.axis_index(POD_AXIS)
            Q3_p = lax.dynamic_slice_in_dim(Q3, pi * k2, k2, axis=0)
            Q = Q1 @ (Q2_i @ Q3_p)  # (n_loc, k3)
            return Q, R

        return run_hier(X)

    n_shards = mesh.shape[DATA_AXIS]

    @partial(
        mesh_lib.shard_map,
        mesh=mesh,
        in_specs=P(DATA_AXIS, None),
        out_specs=(P(DATA_AXIS, None), P()),
    )
    def run(X_loc):
        n_loc, d = X_loc.shape
        k1 = min(n_loc, d)
        Q1, R1 = jnp.linalg.qr(X_loc, mode="reduced")  # (n_loc,k1),(k1,d)
        Rs = _gather_axis(R1, DATA_AXIS, n_shards, mesh=mesh)  # replicated
        Q2, R = jnp.linalg.qr(Rs, mode="reduced")  # (P·k1,k2),(k2,d)
        idx = lax.axis_index(DATA_AXIS)
        Q2_i = lax.dynamic_slice_in_dim(Q2, idx * k1, k1, axis=0)
        Q = Q1 @ Q2_i  # (n_loc, k2)
        return Q, R

    return run(X)


#: max accepted ‖QᵀQ − I‖_max from the CholeskyQR2 fast path. Well-conditioned
#: f32 inputs land ~1e-6; the error grows ~cond(X)²·eps, so exceeding this
#: means the Gram squaring lost real information and Householder must run.
_CHOLQR_ORTHO_TOL = 1e-3


@partial(jax.jit, static_argnames=("mesh",))
def _cholqr2_hier_impl(X, *, mesh):
    """CholeskyQR2 with EXPLICIT two-stage Gram reductions for a
    hierarchical mesh — the "within-pod stacking before the cross-pod
    combine" structure of the communication-avoiding tree applied to the
    fast path: each round's (d, d) Gram partials fold over the ICI first
    and only one per pod crosses the DCN
    (:func:`~dask_ml_tpu.parallel.hierarchy.hpsum`, ledger op
    ``tsqr.gram``). Same arithmetic as :func:`_cholesky_qr2` (ridge,
    floor, two rounds); returns ``(Q, R, err)`` with the orthogonality
    error computed in-program (one more metered Gram, ledger op
    ``tsqr.guard``)."""
    @partial(
        mesh_lib.shard_map,
        mesh=mesh,
        in_specs=mesh_lib.data_pspec(mesh),
        out_specs=(mesh_lib.data_pspec(mesh), P(), P()),
    )
    def run(X_loc):
        d = X_loc.shape[1]

        def one(Yc):
            G = hier.hpsum(Yc.T @ Yc, mesh, op="tsqr.gram")
            ridge = (1e-6 * jnp.trace(G) / d
                     + jnp.finfo(G.dtype).tiny * 1e6)
            G = G + ridge * jnp.eye(d, dtype=G.dtype)
            L = jnp.linalg.cholesky(G)
            Qc = jax.lax.linalg.triangular_solve(
                L, Yc, left_side=False, lower=True, transpose_a=True)
            return Qc, L.T

        Q1, R1 = one(X_loc)
        Q2, R2 = one(Q1)
        QtQ = hier.hpsum(Q2.T @ Q2, mesh, op="tsqr.guard")
        err = jnp.max(jnp.abs(QtQ - jnp.eye(d, dtype=QtQ.dtype)))
        return Q2, R2 @ R1, err

    return run(X)


@partial(jax.jit, static_argnames=("mesh",))
def _tsqr_impl(X, *, mesh):
    """Thin QR of a row-sharded tall-skinny array: CholeskyQR2 fast path
    with an orthogonality guard, falling back to Householder tsqr.

    CholeskyQR2 (two rounds of Gram→Cholesky→triangular-solve) keeps every
    FLOP on the MXU — measured 57× faster than the per-shard Householder
    panels at the PCA bench shape (500k×1000) — but one Gram squares the
    condition number, so for cond(X) ≳ 1/√eps_f32 (~3e3) the factor
    degrades. The guard measures the ACTUAL orthogonality error
    ‖QᵀQ − I‖_max (one extra d×d Gram pass — cheap next to the two rounds,
    and robust where diag(R) condition estimates can underestimate badly)
    and a ``lax.cond`` dispatches to the Householder branch only when the
    fast factor is bad, so the whole thing stays ONE XLA program usable
    inside outer jits. X = Q·R holds exactly for the fast path regardless of
    the guard (Q is defined as X·R⁻¹), so the guard is purely about how
    orthonormal Q is.

    Falls back statically to Householder when per-shard rows < d (the fast
    path's (n, d) output shape needs full column rank per the Gram).

    On a hierarchical ``('pod', 'chip')`` mesh both branches restructure
    as reduce-within-pod-then-across-DCN: the fast path's Gram rounds go
    through :func:`_cholqr2_hier_impl`, the fallback through the
    three-level tree in :func:`_tsqr_householder_impl` — per-axis traffic
    metered in the ledger either way. The flat-mesh program is untouched.
    """
    n_shards = mesh_lib.n_data_shards(mesh)
    n, d = X.shape
    # the exact factorization stays ≥ f32 (docs/precision.md): a bf16 Gram
    # would square bf16's 8-bit mantissa loss into the factor, and the
    # orthogonality guard below is calibrated for f32 — low-precision
    # inputs upcast once here (a static dtype decision, part of the jit
    # signature). The mixed-precision win for the randomized path is the
    # SKETCH, not the repair — see _svd_compressed_impl.
    X = X.astype(px.state_dtype(X.dtype))
    if n // n_shards < d:
        # short shards: Householder handles the k1 = n_loc < d shapes
        return _tsqr_householder_impl(X, mesh=mesh)

    if mesh_lib.is_hierarchical(mesh):
        Qf, Rf, err = _cholqr2_hier_impl(X, mesh=mesh)
    else:
        Qf, Rf = _cholesky_qr2(X)
        # the flat fast path's Gram reductions are GSPMD-implicit (plain
        # sharded matmuls); record their combining bytes here so the
        # ledger's flat-vs-hierarchical comparison covers the same ops
        # (two CholeskyQR2 rounds + the guard below, one (d, d) each)
        for op in ("tsqr.gram", "tsqr.gram", "tsqr.guard"):
            hier.record_collective(op, mesh, (d, d), X.dtype)
        err = jnp.max(jnp.abs(
            Qf.T @ Qf - jnp.eye(d, dtype=Qf.dtype)))  # psum over shards
    return lax.cond(
        err < _CHOLQR_ORTHO_TOL,
        lambda X: (Qf, Rf),
        lambda X: _tsqr_householder_impl(X, mesh=mesh),
        X,
    )


@jax.jit
def _mask_padding_rows(X, weights):
    """Zero out padding rows (weight 0). The factorizations below are only
    correct when padding rows are exact zeros; passing ``weights`` makes that
    an enforced property instead of a caller convention (a centered-but-
    unmasked array would otherwise silently produce wrong factors)."""
    return X * (weights > 0).astype(X.dtype)[:, None]


def tsqr(X, mesh: Optional[jax.sharding.Mesh] = None, weights=None):
    """Thin QR of a row-sharded tall-skinny array.

    Returns ``(Q, R)`` with Q sharded like X (``P('data', None)``) and R
    replicated. Requires the feature axis unsharded — the same single-block
    constraint the reference enforces (reference: utils.py:120-125).
    ``weights`` (optional row weights) masks padding rows to exact zeros.
    Runs guarded CholeskyQR2 with Householder fallback (see
    :func:`_tsqr_impl`); note R's diagonal is positive on the fast path and
    sign-unnormalized on the fallback — downstream SVD composition is
    sign-insensitive and ``svd_flip`` fixes output determinism."""
    mesh = mesh or mesh_lib.default_mesh()
    if weights is not None:
        X = _mask_padding_rows(X, weights)
    return _tsqr_impl(X, mesh=mesh)


@partial(jax.jit, static_argnames=("mesh",))
def _tsvd_impl(X, *, mesh):
    # SVD via tsqr composition: the small R is replicated, so its SVD is
    # replicated compute and U = Q·U_r is a plain sharded matmul.
    Q, R = _tsqr_impl(X, mesh=mesh)
    Ur, S, Vt = jnp.linalg.svd(R, full_matrices=False)
    return Q @ Ur, S, Vt


def tsvd(X, mesh: Optional[jax.sharding.Mesh] = None, weights=None):
    """Thin SVD via tsqr (the ``da.linalg.svd`` analogue, used by the
    reference at pca.py:233, truncated_svd.py:164). U sharded, S/Vt
    replicated. ``weights`` masks padding rows to exact zeros."""
    mesh = mesh or mesh_lib.default_mesh()
    if weights is not None:
        X = _mask_padding_rows(X, weights)
    return _tsvd_impl(X, mesh=mesh)


def _cholesky_qr2(Y):
    """Orthonormalize a tall-skinny sharded Y via CholeskyQR2.

    The MXU-native alternative to Householder QR for the randomized-SVD
    range finder: two rounds of (Gram matmul → small Cholesky → triangular
    solve), every FLOP a matmul/trsm, no sequential panel factorization.
    Measured ~4× cheaper than the per-shard Householder tsqr at the
    PCA-100 bench shape (500k×110). One round loses ~cond(Y)²·eps of
    orthogonality (the Gram squares the condition number); the second
    round repairs it whenever cond(Y) ≲ 1/√eps. The randomized path uses
    it unguarded (each power iteration re-orthonormalizes, so the cond²
    sensitivity never compounds); the exact path (:func:`_tsqr_impl`)
    adds an orthogonality guard with Householder fallback. A relative
    ridge on the Gram keeps the Cholesky PD at f32 even for nearly
    rank-deficient Y.
    """
    def one(Yc):
        G = Yc.T @ Yc  # (ell, ell) replicated; psum over the sharded axis
        ell = G.shape[0]
        # relative ridge + ABSOLUTE floor: an all-zero Y (constant features
        # centered away, fully-masked shard) has trace 0, and cholesky of a
        # zero matrix is NaN — the floor keeps the factor PD and yields
        # exact-zero singular values downstream, like the Householder path
        ridge = 1e-6 * jnp.trace(G) / ell + jnp.finfo(G.dtype).tiny * 1e6
        G = G + ridge * jnp.eye(ell, dtype=G.dtype)
        L = jnp.linalg.cholesky(G)
        Qc = jax.lax.linalg.triangular_solve(
            L, Yc, left_side=False, lower=True, transpose_a=True)
        return Qc, L.T

    Q1, R1 = one(Y)
    Q2, R2 = one(Q1)
    return Q2, R2 @ R1


@partial(jax.jit, static_argnames=("k", "n_power_iter", "n_oversamples",
                                   "compute_dtype"))
def _svd_compressed_impl(X, key, *, k, n_power_iter, n_oversamples,
                         compute_dtype=None):
    # mesh-free since the CholeskyQR2 swap: every op is a plain matmul /
    # replicated small factorization whose sharding GSPMD infers from X.
    #
    # Mixed precision (docs/precision.md): ``compute_dtype`` sets the
    # operand dtype of every X-touching matmul — the sketch Y = X·Ω, the
    # power-iteration passes, and the B = Qᵀ·X projection — all of which
    # accumulate f32 (``px.pdot``). This is the Halko/Martinsson/Tropp
    # structure that makes a low-precision sketch provably safe: the
    # range finder only needs Y to SPAN the dominant subspace (rounding Ω
    # and X to bf16 is one more random perturbation of a random test
    # matrix), while the CholeskyQR2 repair, the small QR/SVD, and the
    # final compositions stay f32 — exactly the split the ISSUE names.
    d = X.shape[1]
    ell = min(k + n_oversamples, d)
    cd = compute_dtype if compute_dtype is not None else X.dtype
    Xc = X.astype(cd)
    sdt = px.state_dtype(X.dtype)
    omega = jax.random.normal(key, (d, ell), cd)
    # Range finder: Y = X·Ω is a sharded (n, ell) matmul on the MXU —
    # low-precision operands, f32 accumulation, f32 result for the repair.
    Y = px.pmatmul(Xc, omega, accum=sdt)
    Q, _ = _cholesky_qr2(Y)
    for _ in range(n_power_iter):
        # QR-stabilized power iteration (the da.linalg.svd_compressed
        # ``n_power_iter`` loop). Z = Xᵀ·Q contracts the sharded axis → psum.
        Z = px.pdot(Xc, Q.astype(cd), (((0,), (0,)), ((), ())),
                    accum=sdt)  # (d, ell) replicated
        W, _ = jnp.linalg.qr(Z, mode="reduced")
        Q, _ = _cholesky_qr2(px.pmatmul(Xc, W.astype(cd), accum=sdt))
    # B = Qᵀ·X, replicated — psum over the sharded contraction; the small
    # SVD of B stays f32
    B = px.pdot(Q.astype(cd), Xc, (((0,), (0,)), ((), ())), accum=sdt)
    Ub, S, Vt = jnp.linalg.svd(B, full_matrices=False)
    U = Q @ Ub  # (n, ell) sharded, f32
    return U[:, :k], S[:k], Vt[:k]


def svd_compressed(X, k: int, n_power_iter: int = 0, key=None,
                   n_oversamples: int = 10,
                   mesh: Optional[jax.sharding.Mesh] = None, weights=None,
                   compute_dtype="policy"):
    """Randomized truncated SVD (Halko et al. 2009) — the
    ``da.linalg.svd_compressed`` analogue (used by the reference at
    pca.py:236-241). ``weights`` masks padding rows to exact zeros (the
    ``Xᵀ·Q`` / ``Qᵀ·X`` contractions would otherwise pick up whatever the
    caller left in the padding rows).

    ``compute_dtype`` is the sketch/matmul operand dtype (the range finder
    tolerates low precision; the CholeskyQR2 repair and small SVD stay
    f32). The default ``"policy"`` resolves the active precision policy's
    ``"sketch"`` op override (then its global compute dtype) at call time
    — resolved HERE, outside the jit, so the policy lands in the compile
    key as a static argument; ``None`` follows X's dtype."""
    if compute_dtype == "policy":
        compute_dtype = px.resolve().compute_for("sketch")
    del mesh  # accepted for API compat; the CholeskyQR2 impl is mesh-free
    if key is None:
        key = jax.random.key(0)
    if weights is not None:
        X = _mask_padding_rows(X, weights)
    return _svd_compressed_impl(X, key, k=int(k),
                                n_power_iter=int(n_power_iter),
                                n_oversamples=int(n_oversamples),
                                compute_dtype=compute_dtype)


# canonical home is the utils layer (as in the reference, utils.py:18-25);
# re-exported here because every decomposition caller reaches it as
# linalg.svd_flip. Living in utils.validation (a leaf module) keeps
# utils/__init__ from importing ops at package-init time (circular).
from dask_ml_tpu.utils.validation import svd_flip  # noqa: E402,F401
