"""Learned fast-transform operator family for sketched k-means
(QuicK-means, Giffon et al., arxiv 1908.08713).

The exact assignment contraction is O(n·k·d). QuicK-means replaces the
dense center matrix C (k, d) with ``C ≈ G · Wᵀ`` where W is a product of
SPARSE orthogonal factors (a learned fast transform) and G is a k-row
sketch supported on p ≪ d transform columns. Because W is orthogonal,
``‖x − Wᵀg‖² = ‖Wx − g‖²``: transform the DATA once (O(n·d·log d),
amortized over every subsequent assignment), and each assignment pass
becomes a (n, p) × (p, k) contraction — O(n·k·p) instead of O(n·k·d).

The operator here is a product of Givens BUTTERFLY sweeps interleaved
with fixed permutations. One sweep is log₂(d_pad) levels; level ℓ pairs
lanes at stride 2^ℓ inside groups of 2·stride and rotates each pair by
its own angle, so every trainable factor is exactly 2-sparse per row and
the whole product stays orthogonal by construction (no projection step
needed to keep the factors feasible, unlike free-form palm4MSA sparse
factors). A single butterfly ladder can only mix lanes at power-of-two
distances, which leaves residual center energy stranded at the other
distances — the classic FFT fix applies: put a fixed (non-trainable)
permutation in front of every sweep after the first, exactly the role
bit-reversal plays inside the FFT factorization. Permutations are
orthogonal and cost one gather, so the product stays fast and exactly
invertible; sweep r's permutation is derived deterministically from r
(``jax.random.permutation(PRNGKey(r), d_pad)``, identity for r = 0), so
it is part of the operator *family*, not a stored parameter.

The sketch G uses one GLOBAL column support shared by all k centers
(``support`` (p,) distinct transform columns + dense ``vals`` (k, p))
rather than per-center sparsity: a shared support turns assignment into a
dense gather-then-matmul that wins on any backend (per-center supports
need a gather per nonzero and lose to the dense contraction on memory
traffic), and it makes the sketched Lloyd M-step exact — restricting the
transformed data to ``support`` and running the ORDINARY weighted-mean
M-step there IS the full-space M-step followed by re-projection onto the
transform product (mean of restrictions == restriction of the mean).

Fitting (:func:`palm4msa_fit`) is the palm4MSA alternation of QuicK-means
specialized to this parameterization, with both blocks solved in CLOSED
FORM. The angle block is one parallel-Jacobi sweep: for each lane pair
(a, b) the angle that maximizes the energy concentrated in the a-lane of
the transformed centers is ``θ = −½·atan2(2·S_ab, S_aa − S_bb)`` (the
2×2 symmetric eigenproblem), computed for every pair of every level from
the paired column statistics of the current transformed centers. The
sketch block is the exact prox: top-p transform columns by total center
energy — for an orthogonal W the off-support column energy IS the
squared reconstruction error. A sweep-granular monotone accept keeps the
best prefix of sweeps (including the zero-sweep identity), so the fit
can never end worse than its identity init: with ``p ≥`` the number of
energetic columns the identity start is already a zero-loss fixed point
and the fit returns it unchanged, angles exactly zero.

Compute precision follows the policy facade
(:func:`dask_ml_tpu.parallel.precision.fast_transform_dtype`): the factor
fit and the transform application run at an f32 floor regardless of the
bf16 data wire — rotation angles are solver state, exactly the
silent-low-precision-state case ``state_dtype`` exists to close — and
:func:`ft_apply` casts back to the data dtype on the way out so the
staging wire contract is preserved.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def _pad_dim(d: int) -> int:
    """Smallest power of two ≥ d (min 2): the butterfly levels need a
    power-of-two lane count; extra columns are zero-padded and carry no
    energy (the top-p support never selects them at identity)."""
    return max(2, 1 << (int(d) - 1).bit_length())


@jax.tree_util.register_pytree_node_class
class FastTransform:
    """A product of Givens butterfly sweeps over ``d_pad`` lanes with a
    fixed permutation in front of every sweep after the first, acting on
    row vectors: ``z = ft_apply(ft, x)`` computes ``x · Wᵀ`` level by
    level. ``angles`` is the (n_sweeps · log₂(d_pad), d_pad//2)
    trainable parameter array — row ℓ holds the rotation angle of every
    lane pair at stride ``2^(ℓ mod log₂ d_pad)``; the permutations are
    derived from the sweep index and carry no parameters. Registered as
    a pytree (angles are the children, the static (d, d_pad) the aux
    data), so the object passes through ``jax.jit``/``jax.grad`` like
    any array."""

    def __init__(self, angles, d: int, d_pad: int):
        self.angles = angles  # (n_sweeps * log2(d_pad), d_pad // 2)
        self.d = int(d)
        self.d_pad = int(d_pad)

    @property
    def levels(self) -> int:
        return self.angles.shape[0]

    def tree_flatten(self):
        return (self.angles,), (self.d, self.d_pad)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], *aux)


def identity(d: int) -> FastTransform:
    """The one-sweep zero-angle transform: sweep 0 has no permutation
    and ``cos 0 = 1``/``sin 0 = 0`` are exact in every float dtype, so
    ``ft_apply`` is the exact identity (modulo zero-padding)."""
    dp = _pad_dim(d)
    levels = dp.bit_length() - 1
    return FastTransform(jnp.zeros((levels, dp // 2), jnp.float32), d, dp)


def _sweep_perm(r: int, d_pad: int):
    """Fixed lane permutation in front of sweep r (None ⇒ identity for
    sweep 0). Derived deterministically from the sweep index so the
    permutations are structure, not state — every caller (fit, apply,
    transpose) reconstructs the same sequence."""
    if r == 0:
        return None
    return jax.random.permutation(jax.random.PRNGKey(r), d_pad)


def _rotate_level(Z, theta, stride: int):
    """One butterfly factor: pair lanes (i, i + stride) inside groups of
    2·stride and rotate each pair by its own angle. The reshape makes the
    2-sparsity structural — the factor never materializes."""
    n, dp = Z.shape
    g = dp // (2 * stride)
    Zr = Z.reshape(n, g, 2, stride)
    th = theta.reshape(1, g, stride).astype(Z.dtype)
    c, s = jnp.cos(th), jnp.sin(th)
    a, b = Zr[:, :, 0, :], Zr[:, :, 1, :]
    return jnp.stack([c * a - s * b, s * a + c * b],
                     axis=2).reshape(n, dp)


def _apply_levels(Z, angles, d_pad: int, transpose: bool):
    """Shared forward/transpose ladder: the transpose of an orthogonal
    product is its inverse — the same factors with negated angles in
    reverse order and inverse permutations (ONE definition, so
    apply/apply_t can never drift, and the FIT applies exactly this
    ladder, so fit and inference can't drift either)."""
    n_levels = int(angles.shape[0])
    L = d_pad.bit_length() - 1
    n_sweeps = n_levels // L
    if transpose:
        for r in range(n_sweeps - 1, -1, -1):
            for lvl in range(L - 1, -1, -1):
                Z = _rotate_level(Z, -angles[r * L + lvl], 1 << lvl)
            prm = _sweep_perm(r, d_pad)
            if prm is not None:
                Z = jnp.take(Z, jnp.argsort(prm), axis=1)
    else:
        for r in range(n_sweeps):
            prm = _sweep_perm(r, d_pad)
            if prm is not None:
                Z = jnp.take(Z, prm, axis=1)
            for lvl in range(L):
                Z = _rotate_level(Z, angles[r * L + lvl], 1 << lvl)
    return Z


def _pad_cols(X, d_pad: int):
    d = X.shape[1]
    if d == d_pad:
        return X
    return jnp.pad(X, ((0, 0), (0, d_pad - d)))


def ft_apply(ft: FastTransform, X):
    """``X (n, d) → Z (n, d_pad)``: zero-pad to the butterfly width and
    run the factor ladder at the policy compute dtype (f32 floor —
    :func:`~dask_ml_tpu.parallel.precision.fast_transform_dtype`), then
    cast back to the data dtype so the staging wire is preserved. For
    the one-sweep zero-angle :func:`identity` transform this is the
    exact identity on the first d columns."""
    from dask_ml_tpu.parallel.precision import fast_transform_dtype

    ct = fast_transform_dtype(X.dtype)
    Z = _pad_cols(X, ft.d_pad).astype(ct)
    Z = _apply_levels(Z, ft.angles, ft.d_pad, transpose=False)
    return Z.astype(X.dtype)


def ft_apply_t(ft: FastTransform, Z):
    """``Z (n, d_pad) → (n, d_pad)`` through ``W`` (the transpose ladder
    — for this orthogonal product, also the inverse: ``ft_apply_t(ft,
    ft_apply(ft, X))`` recovers X up to roundoff, exactly at zero
    angles). Callers wanting data-space rows slice ``[:, :ft.d]``."""
    from dask_ml_tpu.parallel.precision import fast_transform_dtype

    ct = fast_transform_dtype(Z.dtype)
    out = _apply_levels(Z.astype(ct), ft.angles, ft.d_pad, transpose=True)
    return out.astype(Z.dtype)


def sketch_project(ft: FastTransform, centers, p: int):
    """The EXACT sketch prox for a fixed transform: transform the centers,
    keep the p columns with the largest total energy (one shared support —
    see the module docstring for why global, not per-center), restrict.
    Returns ``(support (p,) int32 sorted distinct, vals (k, p) f32)``.
    For orthogonal W the dropped energy ``Σ_offsupport T²`` IS the
    squared reconstruction error — this step is optimal, not heuristic."""
    T = ft_apply(ft, centers.astype(jnp.float32))  # (k, d_pad) f32
    energy = jnp.sum(T * T, axis=0)
    p = min(int(p), ft.d_pad)
    _, support = jax.lax.top_k(energy, p)
    support = jnp.sort(support).astype(jnp.int32)
    return support, jnp.take(T, support, axis=1)


def support_matrix(ft: FastTransform, support):
    """Dense (d, p) slice ``Wᵀ[:d, support]`` of the transform: the thin
    matrix that maps raw data rows straight to their support-restricted
    transform coordinates, ``Z_p = (X − μ) @ support_matrix(ft, s)``.

    This is the production staging path. Running the factor ladder over
    the data costs O(n·d_pad) PER LEVEL — sweeps·log₂(d_pad)
    memory-bound passes that dwarf the assignment contraction being
    bought. But only p ≪ d_pad transform columns are ever consumed, so
    materializing the slice once (apply the ladder to the identity —
    O(d_pad²·levels), independent of n) turns staging into a single
    O(n·d·p) matmul on the MXU. The fast-transform STRUCTURE still does
    its job where it pays: the fit touches only the k center rows and
    stores O(d log d) angles instead of a dense d×d rotation."""
    E = jnp.eye(ft.d_pad, dtype=jnp.float32)
    Wt = _apply_levels(E, ft.angles, ft.d_pad, transpose=False)
    return jnp.take(Wt[: ft.d, :], support, axis=1)


def reconstruct(ft: FastTransform, vals, support):
    """Dense data-space centers ``Ĉ = G · Wᵀ`` (k, d) from a sketch:
    scatter onto the support, run the transpose ladder, drop padding."""
    k = vals.shape[0]
    G = jnp.zeros((k, ft.d_pad), jnp.float32)
    G = G.at[:, support].set(vals.astype(jnp.float32))
    return ft_apply_t(ft, G)[:, : ft.d]


def sketch_loss(ft: FastTransform, centers, support):
    """Squared reconstruction error of the support-restricted sketch at
    the current angles — by orthogonality, the off-support column energy
    of the transformed centers (no reconstruction pass needed)."""
    T = ft_apply(ft, centers.astype(jnp.float32))
    keep = jnp.zeros((ft.d_pad,), jnp.float32).at[support].set(1.0)
    off = T * (1.0 - keep)[None, :]
    return jnp.sum(off * off)


@partial(jax.jit, static_argnames=("p", "n_sweeps", "d", "d_pad"))
def _palm4msa_impl(Cp, *, p: int, n_sweeps: int, d: int, d_pad: int):
    L = d_pad.bit_length() - 1
    k = Cp.shape[0]

    def off_top_energy(T):
        en = jnp.sum(T * T, axis=0)
        return jnp.sum(en) - jnp.sum(jax.lax.top_k(en, p)[0])

    # Run every sweep, recording the sketch loss after each. Each level's
    # angle is the closed-form 2×2 concentrator for its lane pairs:
    # θ = −½·atan2(2·S_ab, S_aa − S_bb) maximizes the post-rotation
    # a-lane energy Σ_centers a'², i.e. one parallel-Jacobi step on the
    # center column-energy matrix restricted to this level's pairing.
    T = Cp
    losses = [off_top_energy(T)]
    rows = []
    for r in range(n_sweeps):
        prm = _sweep_perm(r, d_pad)
        if prm is not None:
            T = jnp.take(T, prm, axis=1)
        for lvl in range(L):
            stride = 1 << lvl
            g = d_pad // (2 * stride)
            Tr = T.reshape(k, g, 2, stride)
            a, b = Tr[:, :, 0, :], Tr[:, :, 1, :]
            Saa = jnp.sum(a * a, axis=0)
            Sbb = jnp.sum(b * b, axis=0)
            Sab = jnp.sum(a * b, axis=0)
            th = (-0.5 * jnp.arctan2(2.0 * Sab, Saa - Sbb)).reshape(-1)
            rows.append(th)
            T = _rotate_level(T, th, stride)
        losses.append(off_top_energy(T))

    # Monotone accept at sweep granularity: keep the best prefix of
    # sweeps (argmin takes the FIRST minimum, so exact ties fall back to
    # the earlier — ultimately the identity — state). Zeroed trailing
    # sweeps still permute, but a permutation can't change the column
    # energy multiset, so the kept loss is exactly the recorded one.
    # Clamp at zero before the argmin: the loss is mathematically >= 0,
    # but f32 sum-minus-top_k can round a later sweep to a tiny negative
    # and steal the tie from the identity state.
    losses = jnp.maximum(jnp.stack(losses), 0.0)
    best = jnp.argmin(losses)
    keep = (jnp.arange(n_sweeps * L) // L) < best
    angles = jnp.stack(rows) * keep[:, None].astype(Cp.dtype)

    # Exact sketch prox for the accepted transform, computed through the
    # SAME ladder inference uses (no fit/apply drift possible).
    T2 = _apply_levels(Cp, angles, d_pad, transpose=False)
    en = jnp.sum(T2 * T2, axis=0)
    _, support = jax.lax.top_k(en, p)
    support = jnp.sort(support).astype(jnp.int32)
    vals = jnp.take(T2, support, axis=1)
    loss = jnp.maximum(jnp.sum(en) - jnp.sum(jnp.take(en, support)), 0.0)
    return angles, support, vals, loss


def palm4msa_fit(centers, p: int, *, n_iter: int = 8):
    """Fit ``(transform, support, vals)`` to dense centers (k, d) by the
    closed-form palm4MSA alternation (see module docstring): ``n_iter``
    permutation-interleaved Jacobi sweeps on the angles, exact top-p
    prox on the sketch, best-prefix monotone accept. Never worse than
    the identity init; identity-EXACT (angles all zero) whenever ``p``
    covers every energetic column. Returns ``(FastTransform, support
    (p,) int32, vals (k, p) f32, loss (f32 scalar))``.

    Callers should center the rows they sketch (k-means geometry is
    translation-invariant and a shared mean component wastes support
    budget on a direction that cancels in every distance comparison) —
    the estimator's sketched path subtracts the weighted data mean
    before fitting and adds it back after reconstruction."""
    from dask_ml_tpu.parallel.precision import fast_transform_dtype

    d = int(centers.shape[1])
    dp = _pad_dim(d)
    ct = fast_transform_dtype(jnp.asarray(centers).dtype)
    Cp = _pad_cols(jnp.asarray(centers, ct), dp).astype(jnp.float32)
    p = min(int(p), dp)
    angles, support, vals, loss = _palm4msa_impl(
        Cp, p=p, n_sweeps=int(n_iter), d=d, d_pad=dp)
    return FastTransform(angles, d, dp), support, vals, loss
