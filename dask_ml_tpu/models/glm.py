"""GLM functional core: families, regularizers, and five native solvers.

The reference delegates every GLM fit to the external ``dask-glm`` package
(reference: linear_model/glm.py:6,157 — ``dask_glm.algorithms._solvers``); the
survey assigns the solver suite itself to this build (SURVEY §2.4, §7.2-5).
This module is that replacement, designed TPU-first:

- A solver iteration is ONE fused XLA program over the sharded data: the
  linear predictor ``X @ beta`` and the gradient pullback ``X.T @ r`` are
  matmuls whose contraction over the sharded sample axis makes XLA insert a
  ``psum`` over the ICI automatically. No per-iteration driver round-trip —
  each solver's full optimization loop is a ``lax.while_loop`` on device
  (the reference pays a dask-graph barrier per iteration; see the same design
  move in :mod:`dask_ml_tpu.models.kmeans`).
- ADMM is the one genuinely per-shard-state algorithm (each data block keeps
  its own primal/dual variables), so it is written with ``jax.shard_map``:
  local Newton prox-solves per shard, consensus z-update via ``psum``
  — the TPU-native analogue of dask-glm's per-chunk ``local_update`` +
  driver-side consensus reduction.
- Gradients and values come from ``jax.value_and_grad`` on the weighted
  objective — no hand-derived gradient code to drift out of sync; curvature
  (Newton / local ADMM Hessians) uses the standard GLM weights
  ``X.T @ diag(w·h(eta)) @ X`` which keeps the FLOPs on the MXU.

Objective convention: with per-row weights ``w`` (0 on padding rows) and
``SW = Σ w``, all solvers minimize

    f(beta) = (1/SW)·Σ w_i·ℓ(x_i·beta, y_i) + (lamduh/SW)·P(beta ⊙ mask)

which has the same minimizer as the reference's sum-loss parameterization
(``lamduh = 1/C``, reference: linear_model/glm.py:118) but is better
conditioned at large n. ``mask`` excludes the intercept column from the
penalty (deliberate deviation from dask-glm, which penalizes the appended
intercept column; unpenalized intercepts match sklearn and are what the
differential tests check).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from dask_ml_tpu.ops import sparse as sparse_ops
from dask_ml_tpu.parallel import hierarchy as hier
from dask_ml_tpu.parallel import precision as px
from dask_ml_tpu.parallel.hierarchy import hpsum
from dask_ml_tpu.parallel.mesh import data_pspec, n_data_shards, shard_map

# ---------------------------------------------------------------------------
# Families: pointwise loss ℓ(eta, y) and curvature h(eta, y) = ∂²ℓ/∂eta²
# (reference counterpart: dask_glm.families used at linear_model/glm.py:86-112)
# ---------------------------------------------------------------------------

_ETA_MAX = 30.0  # clip for exp() links; exp(30) ~ 1e13 stays finite in f32


def _logistic_loss(eta, y):
    # softplus(eta) - y*eta is the numerically stable negative log-likelihood
    return jax.nn.softplus(eta) - y * eta


def _logistic_hess(eta, y):
    p = jax.nn.sigmoid(eta)
    return p * (1.0 - p)


def _normal_loss(eta, y):
    return 0.5 * (eta - y) ** 2


def _normal_hess(eta, y):
    return jnp.ones_like(eta)


def _poisson_loss(eta, y):
    eta = jnp.clip(eta, -_ETA_MAX, _ETA_MAX)
    return jnp.exp(eta) - y * eta


def _poisson_hess(eta, y):
    return jnp.exp(jnp.clip(eta, -_ETA_MAX, _ETA_MAX))


FAMILIES = {
    "logistic": (_logistic_loss, _logistic_hess),
    "normal": (_normal_loss, _normal_hess),
    "poisson": (_poisson_loss, _poisson_hess),
}


# ---------------------------------------------------------------------------
# Regularizers: value P(b) and prox_{t·P}(v)
# (reference counterpart: dask_glm.regularizers selected at glm.py:117-125)
# ---------------------------------------------------------------------------


def _l2_value(b):
    return 0.5 * jnp.sum(b * b)


def _l2_prox(v, t):
    return v / (1.0 + t)


def _l1_value(b):
    return jnp.sum(jnp.abs(b))


def _soft_threshold(v, t):
    return jnp.sign(v) * jnp.maximum(jnp.abs(v) - t, 0.0)


def _en_value(b, weight=0.5):
    return weight * _l1_value(b) + (1.0 - weight) * _l2_value(b)


def _en_prox(v, t, weight=0.5):
    return _soft_threshold(v, weight * t) / (1.0 + (1.0 - weight) * t)


REGULARIZERS = {
    "l2": (_l2_value, _l2_prox),
    "l1": (_l1_value, _soft_threshold),
    "elastic_net": (_en_value, _en_prox),
}


def _penalty(regularizer):
    if regularizer not in REGULARIZERS:
        raise ValueError(
            f"regularizer must be one of {sorted(REGULARIZERS)}, "
            f"got {regularizer!r}"
        )
    return REGULARIZERS[regularizer]


def _make_objective(family, regularizer, smooth_penalty: bool):
    """Weighted-mean objective ``f(beta, X, y, w, lam_eff, mask)``.

    ``smooth_penalty=True`` folds lam·P into the differentiated objective
    (GD/Newton/L-BFGS path); ``False`` leaves P to a prox step (ISTA/ADMM).
    """
    loss_fn, _ = FAMILIES[family]
    pen_value, _ = _penalty(regularizer)

    def objective(beta, X, y, w, lam_eff, mask):
        eta = _data_matvec(X, beta)
        f = jnp.sum(w * loss_fn(eta, y))
        if smooth_penalty:
            f = f + lam_eff * pen_value(beta * mask)
        return f

    return objective


def _state_dtype(X):
    """Optimizer-state dtype for data of X's dtype, routed through the
    precision layer's single state rule (at least float32 — see
    :func:`dask_ml_tpu.parallel.precision.state_dtype` for why the rule is
    a pure function of the data dtype and why low-precision carries are
    structurally impossible): X may be staged bf16 (the matmuls read it on
    the MXU and accumulate f32), but the carries (beta, objective values,
    step sizes, curvature history, ADMM consensus state) stay f32."""
    return px.state_dtype(X.dtype)


def _data_matvec(X, v):
    """``X @ v`` in X's (possibly low) compute dtype with ≥f32 accumulation
    — the precision-aware linear predictor every solver shares. For f32
    data this is the same contraction it replaces; for bf16-staged data the
    coefficient vector is cast down so the matmul feeds the MXU as bf16
    while the output (and therefore gradients, objectives, backtracking
    state) stays f32.

    Sparse dispatch (docs/sparse.md): a staged
    :class:`~dask_ml_tpu.ops.sparse.SparseRows` container routes to the
    blocked-ELL gather/segment-sum kernels — the kernel swap behind this
    stable seam is the whole sparse-GLM story, the solvers above it are
    untouched. Dispatch is BY INPUT TYPE, never a flag: dense inputs take
    the exact contraction they always did, bit-unchanged.

    Under a :func:`~dask_ml_tpu.parallel.hierarchy.model_metered` scope
    (feature-sharded GSPMD fits) the dense contraction additionally records
    its analytic model-axis combining bytes — the (n,)-sized partial-eta
    reduce GSPMD inserts when X's columns shard over 'model'. Recording is
    per-trace inside the jitted program, same discipline as the sparse
    meter."""
    if isinstance(X, sparse_ops.SparseRows):
        return sparse_ops.matvec(X, v)
    hier.record_model_collective("glm.matvec", (int(X.shape[0]),),
                                 px.state_dtype(X.dtype))
    return px.pmatmul(X, v, accum=px.state_dtype(X.dtype))


def _data_pullback(X, r):
    """``X.T @ r`` (the gradient pullback) with the same compute/accum
    discipline as :func:`_data_matvec`: the f32 residual-like vector ``r``
    is cast to X's compute dtype, the contraction over the (possibly
    sharded) sample axis accumulates ≥f32. Sparse containers scatter-add
    through ``segment_sum`` over the stored column indices. Feature-sharded
    fits meter the gradient's model-axis gather (each shard owns a coef
    slice; the full (d,) gradient reassembles across 'model') under the
    :func:`~dask_ml_tpu.parallel.hierarchy.model_metered` scope."""
    if isinstance(X, sparse_ops.SparseRows):
        return sparse_ops.pullback(X, r)
    hier.record_model_collective("glm.pullback", (int(X.shape[1]),),
                                 px.state_dtype(X.dtype))
    return px.pdot(X, r, (((0,), (0,)), ((), ())),
                   accum=px.state_dtype(X.dtype))


def _weighted_gram(X, h):
    """GLM curvature ``X.T @ diag(h) @ X`` with bf16-aware operands and
    ≥f32 accumulation — the d×d Hessian build every Newton path shares.
    ``h`` (f32 per-row curvature weights) is applied first and the product
    cast back to X's dtype, so on bf16 data both matmul operands are bf16
    (MXU-native) while the Hessian itself lands f32 for the dense solve.
    Sparse containers build the same (d, d) matrix by chunked scatter-add
    of per-row nonzero outer products — O(nnz·k), only sensible where a
    dense Hessian is sensible at all. Feature-sharded fits meter the
    Hessian's model-axis assembly (the (d, d) blocks each shard contracts
    gather over 'model' for the replicated-RHS Newton solve) under the
    :func:`~dask_ml_tpu.parallel.hierarchy.model_metered` scope."""
    if isinstance(X, sparse_ops.SparseRows):
        return sparse_ops.weighted_gram(X, h)
    hier.record_model_collective(
        "glm.gram.gather", (int(X.shape[1]), int(X.shape[1])),
        px.state_dtype(X.dtype))
    Xh = (h[:, None] * X).astype(X.dtype)
    return px.pdot(X, Xh, (((0,), (0,)), ((), ())),
                   accum=px.state_dtype(X.dtype))


# ---------------------------------------------------------------------------
# Shared line search: Armijo backtracking as an on-device while_loop
# ---------------------------------------------------------------------------


def _backtrack(obj, beta, f0, g, direction, t0, c=1e-4, shrink=0.5,
               max_back=30):
    """Backtracking line search. Returns (t, f_new, n_backtracks)."""
    gd = jnp.dot(g, direction)

    def cond(state):
        t, f_new, j = state
        insufficient = f_new > f0 + c * t * gd
        return jnp.logical_and(j < max_back,
                               jnp.logical_or(insufficient,
                                              ~jnp.isfinite(f_new)))

    def body(state):
        t, _, j = state
        t = t * shrink
        return t, obj(beta + t * direction), j + 1

    t, f_new, j = lax.while_loop(cond, body,
                                 (t0, obj(beta + t0 * direction), 0))
    return t, f_new, j


# ---------------------------------------------------------------------------
# Solvers
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("family", "regularizer", "max_iter"))
def gradient_descent(X, y, w, beta0, mask, *, family="logistic",
                     regularizer="l2", lamduh=0.0, max_iter=100, tol=1e-4):
    """Batch gradient descent with Armijo backtracking and step growth
    (the dask-glm ``gradient_descent`` analogue; the reference strips the
    regularizer for this solver, linear_model/glm.py:120-122, so the facade
    passes ``lamduh=0``). Whole optimization is one ``lax.while_loop``."""
    obj_full = _make_objective(family, regularizer, smooth_penalty=True)
    sdt = _state_dtype(X)
    sw = jnp.maximum(jnp.sum(w), 1.0)
    lam_eff = jnp.asarray(lamduh, sdt)

    def obj(b):
        return obj_full(b, X, y, w, lam_eff, mask) / sw

    value_and_grad = jax.value_and_grad(obj)

    def cond(state):
        _, _, _, it, done = state
        return jnp.logical_and(it < max_iter, ~done)

    def body(state):
        beta, f, t_prev, it, _ = state
        f0, g = value_and_grad(beta)
        t, f_new, _ = _backtrack(obj, beta, f0, g, -g, t_prev)
        beta_new = beta - t * g
        # Relative-improvement stopping rule, like dask-glm's GD.
        done = jnp.abs(f0 - f_new) <= tol * jnp.maximum(jnp.abs(f0), 1e-10)
        return beta_new, f_new, jnp.minimum(t * 4.0, 1e3), it + 1, done

    init = (beta0.astype(sdt), jnp.asarray(jnp.inf, sdt),
            jnp.asarray(1.0, sdt), jnp.asarray(0, jnp.int32),
            jnp.asarray(False))
    beta, f, _, n_iter, _ = lax.while_loop(cond, body, init)
    return beta, n_iter


@partial(jax.jit, static_argnames=("family", "regularizer", "max_iter"))
def newton(X, y, w, beta0, mask, *, family="logistic", regularizer="l2",
           lamduh=0.0, max_iter=50, tol=1e-4):
    """Damped Newton: GLM Hessian ``X.T @ (w·h · X) / SW`` (a d×d matmul on
    the MXU, psum over the sharded axis), dense solve, Armijo backtracking.
    Reference facade strips the regularizer here too (glm.py:120-122)."""
    loss_fn, hess_fn = FAMILIES[family]
    obj_full = _make_objective(family, regularizer, smooth_penalty=True)
    sdt = _state_dtype(X)
    sw = jnp.maximum(jnp.sum(w), 1.0)
    lam_eff = jnp.asarray(lamduh, sdt)
    d = X.shape[1]
    beta0 = beta0.astype(sdt)

    def obj(b):
        return obj_full(b, X, y, w, lam_eff, mask) / sw

    value_and_grad = jax.value_and_grad(obj)

    def cond(state):
        _, it, done = state
        return jnp.logical_and(it < max_iter, ~done)

    def body(state):
        beta, it, _ = state
        eta = _data_matvec(X, beta)
        # value+gradient in ONE data pass (gd/lbfgs do the same); a separate
        # obj(beta) call would add a redundant O(n·d) traversal per iteration
        f0, g = value_and_grad(beta)
        h = w * hess_fn(eta, y)
        H = _weighted_gram(X, h) / sw
        # Smooth-l2 curvature for the penalized coords + a tiny ridge so the
        # solve never blows up on collinear features.
        H = H + jnp.diag(lam_eff / sw * mask + 1e-8)
        direction = -jnp.linalg.solve(H, g)
        t, _, _ = _backtrack(obj, beta, f0, g, direction, jnp.asarray(1.0, sdt))
        step = t * direction
        beta_new = beta + step
        # Stop on step size OR gradient norm: on rank-deficient designs the
        # minimizer is a flat manifold, the gradient hits the f32 noise floor
        # and Newton would otherwise wander in the Hessian's null space.
        done = jnp.logical_or(jnp.sqrt(jnp.sum(step * step)) < tol,
                              jnp.max(jnp.abs(g)) < tol)
        return beta_new, it + 1, done

    init = (beta0, jnp.asarray(0, jnp.int32), jnp.asarray(False))
    beta, n_iter, _ = lax.while_loop(cond, body, init)
    return beta, n_iter


def _lbfgs_direction(g, S, Y, rho, count, head, m):
    """Two-loop recursion over fixed-size circular history buffers —
    fixed shapes so the whole solver stays inside one compiled program."""

    def bwd(i, carry):
        q, alpha = carry
        idx = (head - 1 - i) % m
        valid = i < count
        a = jnp.where(valid, rho[idx] * jnp.dot(S[idx], q), 0.0)
        q = q - a * Y[idx]
        return q, alpha.at[idx].set(a)

    q, alpha = lax.fori_loop(0, m, bwd, (g, jnp.zeros((m,), g.dtype)))
    newest = (head - 1) % m
    ys = jnp.dot(S[newest], Y[newest])
    yy = jnp.dot(Y[newest], Y[newest])
    gamma = jnp.where(count > 0, ys / jnp.maximum(yy, 1e-30), 1.0)
    r = gamma * q

    def fwd(i, r):
        idx = (head - count + i) % m
        valid = i < count
        b = rho[idx] * jnp.dot(Y[idx], r)
        return r + jnp.where(valid, alpha[idx] - b, 0.0) * S[idx]

    return lax.fori_loop(0, m, fwd, r)


def _lbfgs_loop(obj, value_and_grad, carry0, max_iter, tol, m):
    """The shared L-BFGS while_loop: direction safeguard, Armijo
    backtracking, curvature-pair update, gradient/relative-improvement
    stopping. ``carry0 = (b, g, f, S, Y, rho, count, head)``; returns the
    final 10-tuple carry (``out[8]`` = iterations, ``out[9]`` = done).
    One definition serves both the vector GLM solver (:func:`lbfgs`) and
    the flattened multinomial solver (:func:`multinomial_lbfgs`)."""

    def cond(state):
        _, g, *_rest, it, done = state
        return jnp.logical_and(it < max_iter, ~done)

    def body(state):
        b, g, f, S, Y, rho, count, head, it, _ = state
        direction = -_lbfgs_direction(g, S, Y, rho, count, head, m)
        # Safeguard: fall back to steepest descent if the history produced
        # a non-descent direction (can happen right after a skipped update).
        descent = jnp.dot(g, direction) < 0
        direction = jnp.where(descent, direction, -g)
        t0 = jnp.where(count > 0, 1.0,
                       1.0 / jnp.maximum(jnp.linalg.norm(g), 1.0))
        t, f_new, _ = _backtrack(obj, b, f, g, direction, t0)
        b_new = b + t * direction
        f_new, g_new = value_and_grad(b_new)
        s = b_new - b
        yv = g_new - g
        sy = jnp.dot(s, yv)
        ok = sy > 1e-10
        S = jnp.where(ok, S.at[head].set(s), S)
        Y = jnp.where(ok, Y.at[head].set(yv), Y)
        rho = jnp.where(ok, rho.at[head].set(1.0 / jnp.maximum(sy, 1e-30)),
                        rho)
        head = jnp.where(ok, (head + 1) % m, head)
        count = jnp.where(ok, jnp.minimum(count + 1, m), count)
        gnorm = jnp.max(jnp.abs(g_new))
        rel = jnp.abs(f - f_new) <= tol * jnp.maximum(jnp.abs(f_new), 1e-10)
        done = jnp.logical_or(gnorm < tol, rel)
        return b_new, g_new, f_new, S, Y, rho, count, head, it + 1, done

    init = carry0 + (jnp.asarray(0, jnp.int32), jnp.asarray(False))
    return lax.while_loop(cond, body, init)


@partial(jax.jit, static_argnames=("family", "regularizer", "max_iter", "m",
                                   "return_state"))
def lbfgs(X, y, w, beta0, mask, *, family="logistic", regularizer="l2",
          lamduh=0.0, max_iter=100, tol=1e-4, m=10, state=None,
          return_state=False):
    """L-BFGS with an m-pair circular history, entirely on device.

    The reference shells out to scipy's Fortran L-BFGS-B via dask-glm; here
    the two-loop recursion runs over fixed-shape (m, d) buffers inside the
    same ``lax.while_loop`` as the data passes, so multi-chip meshes never
    sync with the host mid-solve. Like dask-glm, an l1 penalty here is
    handled by subgradient (prefer ``proximal_grad``/``admm`` for sparsity).

    Checkpoint/resume (SURVEY §5.4): ``state`` is the full optimizer carry
    ``(beta, g, f, S, Y, rho, count, head)`` from a previous call with
    ``return_state=True``; resuming from it preserves the curvature history
    exactly, so a chunked run (:func:`dask_ml_tpu.checkpoint.solve_checkpointed`)
    takes the same trajectory as an uninterrupted one. ``n_iter`` counts only
    the iterations performed in THIS call. With ``return_state=True`` the
    return is ``(beta, n_iter, state, done)`` — ``done`` is the loop's own
    convergence flag, so a caller chunking iterations can distinguish
    "converged" from "ran out of budget on the last iteration" (ADVICE r3).
    """
    obj_full = _make_objective(family, regularizer, smooth_penalty=True)
    sdt = _state_dtype(X)
    sw = jnp.maximum(jnp.sum(w), 1.0)
    lam_eff = jnp.asarray(lamduh, sdt)
    d = X.shape[1]
    beta0 = beta0.astype(sdt)

    def obj(b):
        return obj_full(b, X, y, w, lam_eff, mask) / sw

    value_and_grad = jax.value_and_grad(obj)

    if state is None:
        f0, g0 = value_and_grad(beta0)
        carry0 = (beta0, g0, f0,
                  jnp.zeros((m, d), sdt), jnp.zeros((m, d), sdt),
                  jnp.zeros((m,), sdt), jnp.asarray(0, jnp.int32),
                  jnp.asarray(0, jnp.int32))
    else:
        carry0 = tuple(jnp.asarray(s) for s in state)
    out = _lbfgs_loop(obj, value_and_grad, carry0, max_iter, tol, m)
    if return_state:
        return out[0], out[8], out[:8], out[9]
    return out[0], out[8]


@partial(jax.jit, static_argnames=("family", "regularizer", "max_iter"))
def proximal_grad(X, y, w, beta0, mask, *, family="logistic",
                  regularizer="l1", lamduh=0.0, max_iter=100, tol=1e-4):
    """Proximal gradient (ISTA) with backtracking on the quadratic model —
    the dask-glm ``proximal_grad`` analogue. Prox is applied only to the
    penalized coords (``mask``)."""
    obj_smooth = _make_objective(family, regularizer, smooth_penalty=False)
    _, pen_prox = _penalty(regularizer)
    sdt = _state_dtype(X)
    sw = jnp.maximum(jnp.sum(w), 1.0)
    lam_eff = jnp.asarray(lamduh, sdt) / sw

    def fsmooth(b):
        return obj_smooth(b, X, y, w, 0.0, mask) / sw

    value_and_grad = jax.value_and_grad(fsmooth)

    def prox(v, t):
        return jnp.where(mask > 0, pen_prox(v, t * lam_eff), v)

    def cond(state):
        _, _, _, it, done = state
        return jnp.logical_and(it < max_iter, ~done)

    def body(state):
        beta, f, t, it, _ = state
        f0, g = value_and_grad(beta)

        def bt_cond(s):
            tt, j = s
            z = prox(beta - tt * g, tt)
            dz = z - beta
            quad = f0 + jnp.dot(g, dz) + jnp.sum(dz * dz) / (2.0 * tt)
            return jnp.logical_and(j < 30, fsmooth(z) > quad + 1e-12)

        def bt_body(s):
            tt, j = s
            return tt * 0.5, j + 1

        t, _ = lax.while_loop(bt_cond, bt_body, (t, 0))
        beta_new = prox(beta - t * g, t)
        f_new = fsmooth(beta_new)
        step = jnp.max(jnp.abs(beta_new - beta))
        done = step <= tol * jnp.maximum(jnp.max(jnp.abs(beta)), 1e-10)
        return beta_new, f_new, jnp.minimum(t * 2.0, 1e3), it + 1, done

    init = (beta0.astype(sdt), jnp.asarray(jnp.inf, sdt),
            jnp.asarray(1.0, sdt), jnp.asarray(0, jnp.int32),
            jnp.asarray(False))
    beta, _, _, n_iter, _ = lax.while_loop(cond, body, init)
    return beta, n_iter


@partial(jax.jit, static_argnames=("mesh", "family", "regularizer",
                                   "max_iter", "inner_max_iter"))
def _admm_impl(X, y, w, beta0, x0, u0, mask, lamduh, rho, abstol, reltol,
               inner_tol, *, mesh, family, regularizer, max_iter,
               inner_max_iter):
    """Jitted ADMM body: the hyperparameter scalars are traced arguments so
    repeated fits with the same shapes/mesh hit the compile cache (the other
    four solvers get this via module-level ``@jax.jit``).

    ``x0``/``u0`` are the per-shard primal/dual variables stacked along the
    data axis as ``(n_shards, d)`` arrays (sharded ``P('data', None)``, one
    row per shard) so the whole solver carry can round-trip through a host
    checkpoint (SURVEY §5.4); returns ``(z, n_iter, x, u, done)`` with x/u in
    the same stacked layout and ``done`` the Boyd-stopping convergence flag."""
    loss_fn, hess_fn = FAMILIES[family]
    _, pen_prox = _penalty(regularizer)
    n_shards = n_data_shards(mesh)
    d = X.shape[1]
    d2, d1 = data_pspec(mesh, ndim=2), data_pspec(mesh, ndim=1)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(d2, d1, d1,
                  P(), d2, d2,
                  P(), P(), P(), P(), P(), P()),
        out_specs=(P(), P(), d2, d2, P()),
    )
    def run(X_loc, y_loc, w_loc, z0, x0_loc, u0_loc, mask_, lamduh, rho,
            abstol, reltol, inner_tol):
        sw = jnp.maximum(hpsum(jnp.sum(w_loc), mesh, op="glm.admm.sw"), 1.0)
        lam_eff = lamduh / sw

        # Pointwise dℓ/deta via jax.grad of the summed loss (elementwise, so
        # the gradient of the sum IS the pointwise derivative vector).
        dloss = jax.grad(lambda e: jnp.sum(loss_fn(e, y_loc)))

        def local_newton(x, z, u):
            # argmin_x f_i(x) + (rho/2)||x - z + u||²; f_i = Σ_loc w·ℓ / SW
            def grad_eta(xx):
                # one data pass yields BOTH the gradient and the linear
                # predictor the Hessian weights need
                eta = _data_matvec(X_loc, xx)
                g = (_data_pullback(X_loc, w_loc * dloss(eta)) / sw
                     + rho * (xx - z + u))
                return g, eta

            def nt_cond(s):
                _, g, _, it = s
                return jnp.logical_and(it < inner_max_iter,
                                       jnp.max(jnp.abs(g)) > inner_tol)

            def nt_body(s):
                # carry (xx, g, eta): the condition reads the carried
                # gradient instead of recomputing it, so each inner
                # iteration makes exactly one gradient pass over the shard
                xx, g, eta, it = s
                h = w_loc * hess_fn(eta, y_loc)
                H = _weighted_gram(X_loc, h) / sw
                H = H + rho * jnp.eye(d, dtype=xx.dtype)
                xx_new = xx - jnp.linalg.solve(H, g)
                g_new, eta_new = grad_eta(xx_new)
                return xx_new, g_new, eta_new, it + 1

            g0, eta0 = grad_eta(x)
            xx, _, _, _ = lax.while_loop(
                nt_cond, nt_body, (x, g0, eta0, jnp.asarray(0, jnp.int32)))
            return xx

        def cond(state):
            _, _, _, it, done = state
            return jnp.logical_and(it < max_iter, ~done)

        def body(state):
            z, x, u, it, _ = state
            x = local_newton(x, z, u)
            # the z-consensus: the per-iteration (d,)-vector reduction the
            # hierarchical lowering folds within-pod before crossing DCN
            zbar = hpsum(x + u, mesh, op="glm.admm.consensus") / n_shards
            t = lam_eff / (rho * n_shards)
            z_new = jnp.where(mask_ > 0, pen_prox(zbar, t), zbar)
            u = u + x - z_new
            # Boyd stopping: primal/dual residuals vs abs+rel tolerances.
            pri2 = hpsum(jnp.sum((x - z_new) ** 2), mesh,
                         op="glm.admm.residuals")
            dual = rho * jnp.sqrt(float(n_shards)) * jnp.linalg.norm(z_new - z)
            xnorm2 = hpsum(jnp.sum(x * x), mesh, op="glm.admm.residuals")
            unorm2 = hpsum(jnp.sum(u * u), mesh, op="glm.admm.residuals")
            eps_pri = (jnp.sqrt(float(n_shards * d)) * abstol
                       + reltol * jnp.maximum(jnp.sqrt(xnorm2),
                                              jnp.sqrt(float(n_shards))
                                              * jnp.linalg.norm(z_new)))
            eps_dual = (jnp.sqrt(float(n_shards * d)) * abstol
                        + reltol * rho * jnp.sqrt(unorm2))
            done = jnp.logical_and(jnp.sqrt(pri2) < eps_pri, dual < eps_dual)
            return z_new, x, u, it + 1, done

        # x and u are per-shard state, handed in stacked: each shard's block
        # is its own (1, d) row — already "varying" over the data axis, which
        # lines the while_loop carry types up under shard_map's vma checks.
        init = (z0, x0_loc[0], u0_loc[0],
                jnp.asarray(0, jnp.int32), jnp.asarray(False))
        z, x, u, n_iter, done = lax.while_loop(cond, body, init)
        return z, n_iter, x[None, :], u[None, :], done

    return run(X, y, w, beta0, x0, u0, mask, lamduh, rho, abstol, reltol,
               inner_tol)


def admm(X, y, w, beta0, mask, mesh, *, family="logistic", regularizer="l2",
         lamduh=0.0, rho=1.0, max_iter=250, abstol=1e-4, reltol=1e-2,
         inner_max_iter=20, inner_tol=1e-8, state=None, return_state=False):
    """Consensus ADMM over the data mesh (Boyd et al. §7.1.1).

    The genuinely distributed solver: each shard keeps local primal/dual
    state (x_i, u_i) and solves its prox subproblem with damped Newton on
    its OWN rows — written with ``jax.shard_map`` so the local d×d Hessian
    solves never leave the shard; only the z-consensus and the stopping
    residuals cross the ICI, as ``psum``s. This replaces dask-glm's
    per-chunk ``local_update`` (scipy L-BFGS per block on workers) +
    driver-side soft-threshold consensus.

    The z-update prox uses t = lamduh_eff/(rho·N); padding rows have w=0 and
    drop out of every local sum. Defaults mirror dask-glm's admm
    (rho=1, abstol=1e-4, reltol=1e-2, max_iter=250).

    Checkpoint/resume (SURVEY §5.4): ``state = (z, x, u)`` with x/u the
    per-shard primal/dual variables stacked ``(n_shards, d)``; pass a state
    from a previous ``return_state=True`` call to continue the consensus
    exactly where it stopped. ``n_iter`` counts this call's iterations only,
    and ``return_state=True`` returns ``(z, n_iter, state, done)`` with
    ``done`` the loop's own convergence flag (ADVICE r3).
    Unlike the L-BFGS carry, ADMM state is bound to the data-axis shard
    count (each shard owns its consensus subproblem): resuming on a mesh
    with a different number of shards is rejected. On a hierarchical
    ``('pod', 'chip')`` mesh (parallel/hierarchy.py) the z-consensus and
    stopping residuals lower as reduce-within-pod (ICI) then across pods
    (DCN) with per-axis traffic metered in the ledger; shard count and
    pod-major shard order match the flat mesh over the same devices, so
    state round-trips between the two layouts (and across
    checkpoint/resume on either — tests/test_multihost.py pins the
    2-process hierarchical case).
    """
    dt = _state_dtype(X)  # consensus state stays >= f32 even for bf16 data
    d = X.shape[1]
    n_shards = n_data_shards(mesh)
    if state is None:
        z0 = beta0.astype(dt)
        x0 = jnp.broadcast_to(beta0, (n_shards, d)).astype(dt)
        u0 = jnp.zeros((n_shards, d), dt)
    else:
        z0, x0, u0 = (jnp.asarray(s, dt) for s in state)
        if x0.shape != (n_shards, d) or u0.shape != (n_shards, d):
            raise ValueError(
                f"ADMM state has per-shard x/u of shape {x0.shape}, but this "
                f"mesh has {n_shards} data shards (expected {(n_shards, d)}); "
                "ADMM consensus state cannot move between meshes with "
                "different shard counts"
            )
    scalars = [jnp.asarray(v, dt) for v in (lamduh, rho, abstol, reltol,
                                            inner_tol)]
    z, n_iter, x, u, done = _admm_impl(
        X, y, w, z0, x0, u0, mask, *scalars, mesh=mesh, family=family,
        regularizer=regularizer, max_iter=int(max_iter),
        inner_max_iter=int(inner_max_iter))
    if return_state:
        return z, n_iter, (z, x, u), done
    return z, n_iter


# ---------------------------------------------------------------------------
# Multinomial (softmax) logistic regression
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("mesh", "n_classes", "regularizer",
                                   "max_iter", "inner_max_iter"))
def _admm_multinomial_impl(X, y_idx, w, z0, x0, u0, mask, lamduh, rho,
                           abstol, reltol, inner_tol, *, mesh, n_classes,
                           regularizer, max_iter, inner_max_iter):
    """Softmax consensus ADMM body (see :func:`admm_multinomial`): the
    binary :func:`_admm_impl` with (d, K) coefficient matrices. The local
    prox subproblem's Newton solves the full rho-regularized (dK × dK)
    Hessian — dense and positive definite, built as one einsum over the
    shard's rows (H = Σᵢ wᵢ · xᵢxᵢᵀ ⊗ (diag(pᵢ) − pᵢpᵢᵀ) / SW + ρI)."""
    _, pen_prox = _penalty(regularizer)
    n_shards = n_data_shards(mesh)
    d = X.shape[1]
    K = n_classes
    dK = d * K
    d2, d1 = data_pspec(mesh, ndim=2), data_pspec(mesh, ndim=1)
    d3 = data_pspec(mesh, ndim=3)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(d2, d1, d1,
                  P(), d3, d3,
                  P(), P(), P(), P(), P(), P()),
        out_specs=(P(), P(), d3, d3, P()),
    )
    def run(X_loc, y_loc, w_loc, z0, x0_loc, u0_loc, mask_, lamduh, rho,
            abstol, reltol, inner_tol):
        sw = jnp.maximum(hpsum(jnp.sum(w_loc), mesh, op="glm.admm.sw"), 1.0)
        lam_eff = lamduh / sw
        Yoh = jax.nn.one_hot(y_loc.astype(jnp.int32), K, dtype=z0.dtype)

        def local_newton(x, z, u):
            def grad_probs(B):
                logits = X_loc @ B  # (n_loc, K)
                Pm = jax.nn.softmax(logits, axis=1)
                g = (X_loc.T @ (w_loc[:, None] * (Pm - Yoh))) / sw \
                    + rho * (B - z + u)
                return g, Pm

            def nt_cond(s):
                _, g, _, it = s
                return jnp.logical_and(it < inner_max_iter,
                                       jnp.max(jnp.abs(g)) > inner_tol)

            def nt_body(s):
                B, g, Pm, it = s
                # per-row K×K curvature M_i = diag(p_i) - p_i p_i^T
                M = (Pm[:, :, None] * jnp.eye(K, dtype=Pm.dtype)
                     - Pm[:, :, None] * Pm[:, None, :])
                M = M * w_loc[:, None, None]
                # H[(j,c),(l,k)] = Σᵢ wᵢ xᵢⱼ xᵢₗ M_{i,ck}: the output
                # axis order must be (j, c, l, k) so BOTH reshape axes
                # flatten feature-major, matching g.reshape(dK) — a
                # (j,c,k,l) order silently column-permutes the matrix
                # and Newton diverges on strong-signal data
                H = jnp.einsum("ij,ick,il->jclk", X_loc, M, X_loc) / sw
                H = H.reshape(dK, dK) + rho * jnp.eye(dK, dtype=B.dtype)
                step = jnp.linalg.solve(H, g.reshape(dK)).reshape(d, K)
                B_new = B - step
                g_new, P_new = grad_probs(B_new)
                return B_new, g_new, P_new, it + 1

            g0, P0 = grad_probs(x)
            B, _, _, _ = lax.while_loop(
                nt_cond, nt_body, (x, g0, P0, jnp.asarray(0, jnp.int32)))
            return B

        def cond(state):
            _, _, _, it, done = state
            return jnp.logical_and(it < max_iter, ~done)

        def body(state):
            z, x, u, it, _ = state
            x = local_newton(x, z, u)
            zbar = hpsum(x + u, mesh, op="glm.admm.consensus") / n_shards
            t = lam_eff / (rho * n_shards)
            z_new = jnp.where(mask_[:, None] > 0, pen_prox(zbar, t), zbar)
            u = u + x - z_new
            pri2 = hpsum(jnp.sum((x - z_new) ** 2), mesh,
                         op="glm.admm.residuals")
            dual = (rho * jnp.sqrt(float(n_shards))
                    * jnp.linalg.norm((z_new - z).ravel()))
            xnorm2 = hpsum(jnp.sum(x * x), mesh, op="glm.admm.residuals")
            unorm2 = hpsum(jnp.sum(u * u), mesh, op="glm.admm.residuals")
            eps_pri = (jnp.sqrt(float(n_shards * dK)) * abstol
                       + reltol * jnp.maximum(
                           jnp.sqrt(xnorm2),
                           jnp.sqrt(float(n_shards))
                           * jnp.linalg.norm(z_new.ravel())))
            eps_dual = (jnp.sqrt(float(n_shards * dK)) * abstol
                        + reltol * rho * jnp.sqrt(unorm2))
            done = jnp.logical_and(jnp.sqrt(pri2) < eps_pri,
                                   dual < eps_dual)
            return z_new, x, u, it + 1, done

        init = (z0, x0_loc[0], u0_loc[0],
                jnp.asarray(0, jnp.int32), jnp.asarray(False))
        z, x, u, n_iter, done = lax.while_loop(cond, body, init)
        return z, n_iter, x[None], u[None], done

    return run(X, y_idx, w, z0, x0, u0, mask, lamduh, rho, abstol, reltol,
               inner_tol)


def admm_multinomial(X, y_idx, w, B0, mask, mesh, *, n_classes,
                     regularizer="l2", lamduh=0.0, rho=1.0, max_iter=250,
                     abstol=1e-4, reltol=1e-2, inner_max_iter=20,
                     inner_tol=1e-8, state=None, return_state=False):
    """Consensus ADMM for SOFTMAX logistic regression (Boyd §7.1.1 with
    matrix-valued per-shard variables) — closes the binary solver suite's
    last multiclass gap: every shard keeps (d, K) primal/dual state and
    solves its softmax prox subproblem with full-Hessian Newton on its
    own rows; only the (d, K) z-consensus and the stopping residuals
    cross the ICI as psums. Same carry/checkpoint contract as
    :func:`admm` with ``state = (z, x, u)``, x/u stacked
    ``(n_shards, d, K)``. Returns ``(B (d, K), n_iter)``."""
    dt = _state_dtype(X)
    d = X.shape[1]
    K = int(n_classes)
    n_shards = n_data_shards(mesh)
    if state is None:
        z0 = B0.astype(dt)
        x0 = jnp.broadcast_to(B0, (n_shards, d, K)).astype(dt)
        u0 = jnp.zeros((n_shards, d, K), dt)
    else:
        z0, x0, u0 = (jnp.asarray(s, dt) for s in state)
        if x0.shape != (n_shards, d, K) or u0.shape != (n_shards, d, K):
            raise ValueError(
                f"multinomial ADMM state has per-shard x/u of shape "
                f"{x0.shape}; this mesh/problem expects "
                f"{(n_shards, d, K)} — consensus state cannot move "
                "between meshes with different shard counts"
            )
    scalars = [jnp.asarray(v, dt) for v in (lamduh, rho, abstol, reltol,
                                            inner_tol)]
    z, n_iter, x, u, done = _admm_multinomial_impl(
        X, y_idx, w, z0, x0, u0, mask, *scalars, mesh=mesh, n_classes=K,
        regularizer=regularizer, max_iter=int(max_iter),
        inner_max_iter=int(inner_max_iter))
    if return_state:
        return z, n_iter, (z, x, u), done
    return z, n_iter


@partial(jax.jit, static_argnames=("n_classes", "regularizer", "max_iter",
                                   "m", "return_state"))
def multinomial_lbfgs(X, y_idx, w, B0, mask, *, n_classes, regularizer="l2",
                      lamduh=0.0, max_iter=200, tol=1e-4, m=10, state=None,
                      return_state=False):
    """Softmax (multinomial) logistic regression by L-BFGS on the flattened
    (d·K) coefficient vector — one on-device ``lax.while_loop``, the same
    algorithm/stopping rules as :func:`lbfgs` instantiated over the softmax
    cross-entropy objective (parity-plus: dask-glm, and therefore the
    reference, is binary-only).

    ``y_idx`` holds float class indices 0..K-1 (padding rows: any index,
    weight 0); ``mask`` is the per-FEATURE penalty mask (d,), broadcast over
    classes — the intercept row stays unpenalized, matching the binary
    facade. Each iteration is two fused data passes (logits matmul forward,
    Xᵀ·residual pullback inside the gradient), psum'd over the sharded
    sample axis by XLA. Returns ``(B (d, K), n_iter)``. With an l2 penalty
    the softmax shift degeneracy is pinned exactly as sklearn's multinomial
    path pins it.

    Checkpoint/resume follows :func:`lbfgs` exactly: ``state`` is the full
    flattened-vector optimizer carry from a previous ``return_state=True``
    call (curvature history included, so chunked runs take the
    uninterrupted trajectory); with ``return_state=True`` the return is
    ``(B, n_iter, state, done)``.
    """
    n, d = X.shape
    K = n_classes
    sdt = _state_dtype(X)
    sw = jnp.maximum(jnp.sum(w), 1.0)
    pen_value, _ = _penalty(regularizer)
    lam_eff = jnp.asarray(lamduh, sdt)
    Yoh = jax.nn.one_hot(y_idx.astype(jnp.int32), K, dtype=sdt)

    def obj(bflat):
        B = bflat.reshape(d, K)
        if isinstance(X, sparse_ops.SparseRows):
            # sparse logits: gather-matmat through the kernel tier (the
            # gradient's X.T-pullback falls out of autodiff as the
            # segment-sum scatter); the dense expression below stays
            # byte-identical for dense inputs
            logits = sparse_ops.matmat(X, B)
        else:
            logits = jax.lax.dot_general(
                X, B.astype(X.dtype), (((1,), (0,)), ((), ())),
                preferred_element_type=sdt)  # (n, K)
        lse = jax.scipy.special.logsumexp(logits, axis=1)
        nll = jnp.sum(w * (lse - jnp.sum(Yoh * logits, axis=1)))
        pen = pen_value((B * mask[:, None]).ravel())
        return (nll + lam_eff * pen) / sw

    value_and_grad = jax.value_and_grad(obj)
    dK = d * K
    if state is None:
        b0 = B0.astype(sdt).reshape(dK)
        f0, g0 = value_and_grad(b0)
        carry0 = (b0, g0, f0,
                  jnp.zeros((m, dK), sdt), jnp.zeros((m, dK), sdt),
                  jnp.zeros((m,), sdt), jnp.asarray(0, jnp.int32),
                  jnp.asarray(0, jnp.int32))
    else:
        carry0 = tuple(jnp.asarray(s) for s in state)
    out = _lbfgs_loop(obj, value_and_grad, carry0, max_iter, tol, m)
    if return_state:
        return out[0].reshape(d, K), out[8], out[:8], out[9]
    return out[0].reshape(d, K), out[8]


# ---------------------------------------------------------------------------
# Batched regularization-path solves (search fast path)
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("solver", "family", "regularizer",
                                   "max_iter"))
def batched_glm_path(X, y, w, beta0, mask, lamduh_arr, *, solver, family,
                     regularizer, max_iter, tol):
    """Solve the SAME GLM problem for a whole vector of regularization
    strengths as one program: ``jax.vmap`` over ``lamduh`` maps the chosen
    solver's full ``lax.while_loop`` across members (each lane stops
    contributing once converged; the loop runs to the slowest member).

    The batched-candidate analogue of KMeans' trajectory program for the
    search driver (SURVEY §2.9 task-parallelism): a ``C`` grid over a
    LogisticRegression dispatches one program + one score fetch instead of
    one fit and one fetch per candidate. Data is closed over un-mapped, so
    the memory cost is one copy of X plus (M, d) coefficients. ADMM is
    excluded (its shard_map program keeps per-shard state; the facade
    declines batching for it). Returns ``(betas (M, d), n_iters (M,))``.
    """
    table = {
        "gradient_descent": gradient_descent,
        "newton": newton,
        "lbfgs": lbfgs,
        "proximal_grad": proximal_grad,
    }
    fn = table[solver]

    def one(lam):
        return fn(X, y, w, beta0, mask, family=family,
                  regularizer=regularizer, lamduh=lam, max_iter=max_iter,
                  tol=tol)

    return jax.vmap(one)(lamduh_arr)


@partial(jax.jit, static_argnames=("family",))
def batched_eval_scores(E, y, w, betas, *, family):
    """Default scores of a coefficient batch on one eval set, weighted (0
    weights exclude padding rows): accuracy for logistic (matching the
    facade's ``score``), R² for normal. ``betas`` is (M, d); returns (M,)."""
    if isinstance(E, sparse_ops.SparseRows):
        eta = sparse_ops.matmat(E, betas.T)  # (nE, M)
    else:
        eta = E @ betas.T  # (nE, M)
    sw = jnp.maximum(jnp.sum(w), 1e-12)
    if family == "logistic":
        pred = (eta > 0).astype(jnp.float32)
        hit = (pred == y[:, None]).astype(jnp.float32)
        return jnp.sum(hit * w[:, None], axis=0) / sw
    # normal: weighted R² with the standard uniform-average convention
    resid = y[:, None] - eta
    ss_res = jnp.sum(resid * resid * w[:, None], axis=0)
    ybar = jnp.sum(y * w) / sw
    ss_tot = jnp.maximum(jnp.sum((y - ybar) ** 2 * w), 1e-30)
    return 1.0 - ss_res / ss_tot


# ---------------------------------------------------------------------------
# Larger-than-HBM training: streamed consensus ADMM over row blocks
# ---------------------------------------------------------------------------


def _streamed_block_newton(X_b, y_b, w_b, x, z, u, rho, inner_tol, sw_total,
                           *, family, inner_max_iter):
    """One block's local Newton prox-solve — the SINGLE implementation both
    streamed block-source modes run (traced ``block_fn`` scan and the
    host-streamed ``HostBlockSource`` driver), which is what makes their
    trajectories identical."""
    loss_fn, hess_fn = FAMILIES[family]
    d = z.shape[0]
    dloss = jax.grad(lambda e: jnp.sum(loss_fn(e, y_b)))

    def grad_eta(xx):
        eta = _data_matvec(X_b, xx)
        g = (_data_pullback(X_b, w_b * dloss(eta)) / sw_total
             + rho * (xx - z + u))
        return g, eta

    def nt_cond(s):
        _, g, _, it = s
        return jnp.logical_and(it < inner_max_iter,
                               jnp.max(jnp.abs(g)) > inner_tol)

    def nt_body(s):
        xx, g, eta, it = s
        h = w_b * hess_fn(eta, y_b)
        H = _weighted_gram(X_b, h) / sw_total
        H = H + rho * jnp.eye(d, dtype=xx.dtype)
        xx_new = xx - jnp.linalg.solve(H, g)
        g_new, eta_new = grad_eta(xx_new)
        return xx_new, g_new, eta_new, it + 1

    g0, eta0 = grad_eta(x)
    xx, _, _, _ = lax.while_loop(
        nt_cond, nt_body, (x, g0, eta0, jnp.asarray(0, jnp.int32)))
    return xx


def _streamed_consensus(z, x_new, u, mask, lamduh, rho, abstol, reltol,
                        sw_total, *, regularizer):
    """The streamed z-update + Boyd stopping, shared by both block-source
    modes (identical to the sharded solver with n_shards → n_blocks)."""
    _, pen_prox = _penalty(regularizer)
    n_blocks, d = x_new.shape
    lam_eff = lamduh / sw_total
    zbar = jnp.mean(x_new + u, axis=0)
    t = lam_eff / (rho * n_blocks)
    z_new = jnp.where(mask > 0, pen_prox(zbar, t), zbar)
    u_new = u + x_new - z_new
    pri2 = jnp.sum((x_new - z_new) ** 2)
    dual = rho * jnp.sqrt(float(n_blocks)) * jnp.linalg.norm(z_new - z)
    eps_pri = (jnp.sqrt(float(n_blocks * d)) * abstol
               + reltol * jnp.maximum(
                   jnp.sqrt(jnp.sum(x_new * x_new)),
                   jnp.sqrt(float(n_blocks)) * jnp.linalg.norm(z_new)))
    eps_dual = (jnp.sqrt(float(n_blocks * d)) * abstol
                + reltol * rho * jnp.sqrt(jnp.sum(u_new * u_new)))
    done = jnp.logical_and(jnp.sqrt(pri2) < eps_pri, dual < eps_dual)
    return z_new, u_new, done


@partial(jax.jit, static_argnames=("block_fn", "n_blocks", "family",
                                   "regularizer", "max_iter",
                                   "inner_max_iter"))
def _admm_streamed_impl(z0, x0, u0, mask, lamduh, rho, abstol, reltol,
                        inner_tol, sw_total, *, block_fn, n_blocks, family,
                        regularizer, max_iter, inner_max_iter):
    def body(state):
        z, x, u, it, _ = state  # x, u: (B, d)

        def per_block(_, inp):
            b, x_b, u_b = inp
            X_b, y_b, w_b = block_fn(b)
            return None, _streamed_block_newton(
                X_b, y_b, w_b, x_b, z, u_b, rho, inner_tol, sw_total,
                family=family, inner_max_iter=inner_max_iter)

        _, x_new = lax.scan(
            per_block, None,
            (jnp.arange(n_blocks, dtype=jnp.int32), x, u))
        z_new, u_new, done = _streamed_consensus(
            z, x_new, u, mask, lamduh, rho, abstol, reltol, sw_total,
            regularizer=regularizer)
        return z_new, x_new, u_new, it + 1, done

    def cond(state):
        _, _, _, it, done = state
        return jnp.logical_and(it < max_iter, ~done)

    init = (z0, x0, u0, jnp.asarray(0, jnp.int32), jnp.asarray(False))
    z, x, u, n_iter, done = lax.while_loop(cond, body, init)
    return z, n_iter, x, u, done


@partial(jax.jit, static_argnames=("family", "inner_max_iter", "transform"))
def _host_block_prox(blk, b, z, x, u, rho, inner_tol, sw_total, *,
                     family, inner_max_iter, transform):
    """One host-streamed block's prox-solve as a standalone program: the
    block arrives as already-transferred device arrays, the per-block
    primal/dual rows are sliced in-trace, and the (optional) source
    transform — e.g. the facade's intercept append — fuses into the same
    compiled program."""
    if transform is not None:
        blk = transform(blk)
    X_b, y_b, w_b = blk
    x_b = lax.dynamic_index_in_dim(x, b, keepdims=False)
    u_b = lax.dynamic_index_in_dim(u, b, keepdims=False)
    return _streamed_block_newton(
        X_b, y_b, w_b, x_b, z, u_b, rho, inner_tol, sw_total,
        family=family, inner_max_iter=inner_max_iter)


@partial(jax.jit, static_argnames=("regularizer",))
def _host_consensus(z, x_new, u, mask, lamduh, rho, abstol, reltol,
                    sw_total, *, regularizer):
    return _streamed_consensus(z, x_new, u, mask, lamduh, rho, abstol,
                               reltol, sw_total, regularizer=regularizer)


def _admm_streamed_host(source, z0, x0, u0, mask, lamduh, rho, abstol,
                        reltol, inner_tol, sw_total, *, check_done, family,
                        regularizer, max_iter, inner_max_iter,
                        scan_checkpoint=None):
    """Host-driven outer loop over a :class:`HostBlockSource`: block ``b+1``
    transfers (and, across the epoch boundary, block 0 of the next outer
    iteration) while block ``b``'s Newton prox-solve runs. Same math as
    :func:`_admm_streamed_impl` — both modes call
    :func:`_streamed_block_newton` / :func:`_streamed_consensus`.

    ``check_done`` fetches the Boyd convergence flag once per outer
    iteration (one scalar round-trip); the caller disables it when both
    tolerances are exactly 0, keeping the zero-tolerance bench/equivalence
    runs free of per-iteration syncs.

    ``scan_checkpoint`` (a
    :class:`~dask_ml_tpu.parallel.faults.ScanCheckpoint`) makes the loop
    preemption-safe: the scan carry is the epoch-start ``(z, x, u)`` and
    the outs are the per-block primal updates, so a snapshot taken after
    any block replays the rest of that epoch — and the remaining epochs —
    with a bit-identical trajectory. A snapshot found at the path resumes
    here; the file is deleted on completion (it is a resume artifact, and
    a stale one would hijack the next fit at the same path)."""
    from dask_ml_tpu.parallel import telemetry
    from dask_ml_tpu.parallel.stream import prefetched_scan

    n_blocks = int(x0.shape[0])
    z, x, u = z0, x0, u0
    done = jnp.asarray(False)
    n_iter = 0
    b32 = [jnp.asarray(b, jnp.int32) for b in range(n_blocks)]

    start_epoch, start_block, outs0 = 0, 0, None
    if scan_checkpoint is not None:
        snap = scan_checkpoint.load()
        if snap is not None:
            carry, outs0, start_block, start_epoch = snap
            z, x, u = (jnp.asarray(t) for t in carry)
            outs0 = [jnp.asarray(o) for o in outs0]
            n_iter = start_epoch

    def step(carry, b, blk):
        z, x, u = carry
        x_b = _host_block_prox(
            blk, b32[b], z, x, u, rho, inner_tol, sw_total,
            family=family, inner_max_iter=inner_max_iter,
            transform=source.transform)
        return carry, x_b

    for it in range(start_epoch, max_iter):
        first = it == start_epoch
        with telemetry.span("glm.admm.epoch", epoch=it, blocks=n_blocks):
            _, xs = prefetched_scan(
                step, (z, x, u), source, wrap=it + 1 < max_iter,
                checkpoint=scan_checkpoint, epoch=it,
                start_block=start_block if first else 0,
                outs=outs0 if first else None)
            x = jnp.stack(xs)
            # the single-host streamed consensus reduces the whole block
            # stack locally: a ZERO-byte entry on the cross-host ("pod")
            # axis — the zero-collective path the ledger pins must show
            # as exactly 0 (the elastic driver's counterpart records the
            # real cross-host import bytes; parallel/elastic.py)
            from dask_ml_tpu.parallel.hierarchy import ledger
            ledger().record("glm.admm.consensus", "pod", 0)
            with telemetry.span("glm.admm.consensus", epoch=it):
                z, u, done = _host_consensus(
                    z, x, u, mask, lamduh, rho, abstol, reltol, sw_total,
                    regularizer=regularizer)
        n_iter = it + 1
        if check_done and bool(done):
            break
    source.discard_inflight()
    if scan_checkpoint is not None:
        scan_checkpoint.delete()
    return z, jnp.asarray(n_iter, jnp.int32), x, u, done


def admm_streamed(block_fn, n_blocks, d, sw_total, mask=None, *,
                  family="logistic", regularizer="l2", lamduh=0.0, rho=1.0,
                  max_iter=250, abstol=1e-4, reltol=1e-2, inner_max_iter=20,
                  inner_tol=1e-8, state=None, return_state=False,
                  dtype=jnp.float32, checkpoint_path=None,
                  checkpoint_every=None, elastic=None):
    """Consensus ADMM over data LARGER THAN DEVICE MEMORY.

    The sharded :func:`admm` holds all of X in HBM; here each outer
    iteration ``lax.scan``s over ``n_blocks`` row blocks, materializing one
    block at a time via ``block_fn(b) -> (X_b, y_b, w_b)`` INSIDE the scan
    body — the block is resident only for its own inner Newton prox-solve
    and its buffer is reused for the next block, so peak HBM is one block
    plus the O(B·d) consensus state regardless of total data size
    (VERDICT r3 #3: the blueprint's 1e8×100 ADMM config is 40 GB, over a
    single chip's HBM).

    ``block_fn`` is either TRACED or a HOST BLOCK SOURCE:

    - a traced callable REGENERATES blocks on device (synthetic
      benchmarks; nothing ever resident) or slices a resident array
      (testing) inside the compiled scan;
    - a :class:`dask_ml_tpu.parallel.stream.HostBlockSource` streams real
      host-resident blocks through a depth-``source.prefetch``
      double-buffered pipeline — the async ``device_put`` of block b+1
      overlaps block b's inner Newton solve instead of serializing inside
      the scan body (see ``parallel/stream.py`` for why a host-driven
      outer loop beats ``io_callback``-fed buffers here).

    The consensus math is identical to the sharded solver with blocks
    standing in for shards, so B streamed blocks and a B-shard mesh
    produce the same trajectory — in BOTH block-source modes, which share
    one per-block implementation (:func:`_streamed_block_newton`).
    ``sw_total`` is the total sample weight over ALL blocks (= n for unit
    weights), fixing the objective's 1/SW normalization without a
    pre-pass.

    Returns ``(z, n_iter)``; with ``return_state=True``:
    ``(z, n_iter, (z, x, u), done)`` — the same checkpointable carry
    contract as :func:`admm`, with x/u stacked ``(n_blocks, d)``.

    ``dtype`` names the BLOCK (data) dtype only. The consensus state
    (z, x, u), scalars, and mask always live in
    ``precision.state_dtype(dtype)`` — at least f32 — so streaming bf16
    blocks (the wire-halving policy, docs/precision.md) still carries
    full-precision solver state; passing ``dtype=bfloat16`` no longer
    silently runs the consensus arithmetic in bf16.

    Preemption safety (host-source mode only): ``checkpoint_path`` makes
    the fit resumable — every ``checkpoint_every`` completed blocks
    (default: once per outer iteration) the scan state snapshots through
    ``checkpoint.save_pytree``, SIGTERM/SIGINT trigger a graceful drain
    (finish the in-flight block, snapshot, raise
    :class:`~dask_ml_tpu.parallel.faults.Preempted`), and a re-run with
    the same path resumes from the last complete block with a
    bit-identical trajectory (``tests/test_faults.py`` pins this). The
    snapshot is deleted on completion. Traced ``block_fn`` mode rejects
    ``checkpoint_path`` — its whole epoch is one compiled program, so
    chunk it through ``state=``/``return_state`` instead (the
    ``solve_checkpointed`` pattern).

    ``elastic`` (an :class:`~dask_ml_tpu.parallel.elastic.ElasticRun`,
    host-source mode only) spans the epoch over a FLEET of processes:
    this host consumes its shard of the run's seeded block permutation,
    publishes per-block results to the shared workdir, and survivors
    rebalance a lost host's unconsumed blocks mid-epoch — the final
    (z, x, u) trajectory is bit-identical to the uninterrupted
    single-host run whatever the roster did (``parallel/elastic.py``;
    ``docs/robustness.md`` "Elastic epochs"). Composes with
    ``checkpoint_path`` (resume replays the snapshot's own shuffled
    block slice).
    """
    from dask_ml_tpu.parallel.stream import HostBlockSource

    # ``dtype`` names the BLOCK/data dtype; the consensus state, scalars,
    # and mask live in the precision layer's state dtype — at least f32 —
    # so a bf16-storage run never silently carries bf16 solver state (the
    # case the pre-policy code hit when a caller passed dtype=bfloat16:
    # z/x/u would round every consensus update to 8 mantissa bits).
    sdt = px.state_dtype(dtype)
    if state is None:
        z0 = jnp.zeros((d,), sdt)
        x0 = jnp.zeros((n_blocks, d), sdt)
        u0 = jnp.zeros((n_blocks, d), sdt)
    else:
        z0, x0, u0 = (jnp.asarray(s, sdt) for s in state)
        if x0.shape != (n_blocks, d) or u0.shape != (n_blocks, d):
            raise ValueError(
                f"streamed ADMM state has x/u of shapes {x0.shape}/"
                f"{u0.shape}, expected {(n_blocks, d)}; like the sharded "
                "solver, consensus state cannot move between runs with "
                "different block counts")
    if mask is None:
        mask = jnp.ones((d,), sdt)
    scalars = [jnp.asarray(v, sdt) for v in (lamduh, rho, abstol, reltol,
                                             inner_tol, sw_total)]
    if isinstance(block_fn, HostBlockSource):
        if block_fn.n_blocks != int(n_blocks):
            raise ValueError(
                f"n_blocks={n_blocks} does not match the HostBlockSource's "
                f"{block_fn.n_blocks} blocks")
        lam_d, rho_d, abstol_d, reltol_d, tol_d, sw_d = scalars
        from dask_ml_tpu.parallel.faults import scan_checkpoint_scope

        # the bind dict ties the snapshot to its problem (same policy as
        # solve_checkpointed's fingerprint); max_iter is excluded so a
        # resume may extend the iteration budget
        from dask_ml_tpu.parallel import telemetry

        with scan_checkpoint_scope(
                checkpoint_path,
                every=(int(n_blocks) if checkpoint_every is None
                       else int(checkpoint_every)),
                bind={"what": "admm_streamed", "n_blocks": int(n_blocks),
                      "d": int(d), "family": family,
                      "regularizer": regularizer,
                      # elastic snapshots store POSITIONS into a shuffled
                      # block sequence; resuming one as a canonical
                      # range(n_blocks) scan (or vice versa) must be a
                      # loud bind error, never a silent reorder
                      "elastic": elastic is not None,
                      "params": repr((float(lamduh), float(rho),
                                      float(abstol), float(reltol),
                                      float(inner_tol), float(sw_total),
                                      int(inner_max_iter)))}) as scan_ckpt:
            # the root span of the streamed fit's tree; sp.sync attributes
            # the async dispatch backlog (the last epoch's still-running
            # block solves) to the fit instead of the caller's first fetch
            # — a barrier only when telemetry is ON (sync is a no-op on
            # the disabled path, so pipelining is unchanged knob-off)
            with telemetry.span("glm.admm.streamed", blocks=int(n_blocks),
                                d=int(d), family=family) as sp:
                host_kw = dict(
                    check_done=(float(abstol) != 0.0
                                or float(reltol) != 0.0),
                    family=family, regularizer=regularizer,
                    max_iter=int(max_iter),
                    inner_max_iter=int(inner_max_iter),
                    scan_checkpoint=scan_ckpt)
                if elastic is not None:
                    from dask_ml_tpu.parallel.elastic import \
                        elastic_admm_host
                    z, n_iter, x, u, done = elastic_admm_host(
                        elastic, block_fn, z0, x0, u0,
                        jnp.asarray(mask, sdt), lam_d, rho_d, abstol_d,
                        reltol_d, tol_d, sw_d, **host_kw)
                else:
                    z, n_iter, x, u, done = _admm_streamed_host(
                        block_fn, z0, x0, u0, jnp.asarray(mask, sdt),
                        lam_d, rho_d, abstol_d, reltol_d, tol_d, sw_d,
                        **host_kw)
                sp.sync(z)
    else:
        if checkpoint_path is not None:
            raise ValueError(
                "checkpoint_path= requires a HostBlockSource: a traced "
                "block_fn runs each epoch as one compiled program, so "
                "preemption-safe chunking goes through state=/return_state "
                "instead (see checkpoint.solve_checkpointed)")
        if elastic is not None:
            raise ValueError(
                "elastic= requires a HostBlockSource: the elastic data "
                "plane shards host-resident block INGESTION across "
                "processes — a traced block_fn has no host blocks to "
                "shard (parallel/elastic.py)")
        z, n_iter, x, u, done = _admm_streamed_impl(
            z0, x0, u0, jnp.asarray(mask, sdt), *scalars,
            block_fn=block_fn, n_blocks=int(n_blocks), family=family,
            regularizer=regularizer, max_iter=int(max_iter),
            inner_max_iter=int(inner_max_iter))
    if return_state:
        return z, n_iter, (z, x, u), done
    return z, n_iter


# ---------------------------------------------------------------------------
# Streaming (incremental) training: one proximal-SGD step per row block
# ---------------------------------------------------------------------------


def make_sgd_step(family="logistic", regularizer="l2", lamduh=0.0,
                  eta0=0.1, power_t=0.5, fit_intercept=True,
                  n_classes=None):
    """Build the jittable partial_fit step for streaming GLM training.

    Returns ``step(state, (x, y, w)) -> state`` with
    ``state = (beta, t)``: one proximal-SGD update per block — gradient of
    the weighted-mean family loss on the block, step size
    ``eta0 / (1 + t)**power_t``, then the regularizer's prox applied to the
    penalized coordinates (mask excludes the intercept). The capability this
    provides is the reference's ``Incremental``/``_partial.fit`` chain over
    an SGD-style estimator (reference: _partial.py:104-182,
    linear_model/stochastic_gradient.py:7-15); here the whole chain of
    blocks fuses into one ``lax.scan`` via
    :func:`dask_ml_tpu.wrappers.incremental_scan`.

    ``w`` is the per-row weight (0 marks padding in the remainder block, so
    partial blocks are exact, not dropped). ``beta``'s last coordinate is
    the intercept when ``fit_intercept`` — blocks arrive WITHOUT the ones
    column; the step appends it, keeping the caller's block layout identical
    to the batch solvers' convention.

    ``n_classes >= 3`` (logistic family only) switches to the softmax
    generalization: ``beta`` is a (width, K) matrix, ``y`` holds float
    class indices 0..K-1, the block loss is softmax cross-entropy, and the
    prox/intercept handling applies row-wise (each feature row penalized
    across all K columns, the intercept row free) — the streaming analogue
    of :func:`multinomial_lbfgs` (VERDICT r4 #7: the binary path's
    streaming stopped at K=2).
    """
    multinomial = (n_classes is not None and n_classes >= 3)
    if multinomial and family != "logistic":
        raise ValueError("n_classes >= 3 requires family='logistic'")
    loss_fn, _ = FAMILIES[family]
    _, pen_prox = _penalty(regularizer)

    def step(state, blk):
        beta, t = state
        x, y, w = blk
        sparse_blk = isinstance(x, sparse_ops.SparseRows)
        if fit_intercept:
            if sparse_blk:
                x = sparse_ops.add_intercept_ell(x)
            else:
                x = jnp.concatenate(
                    [x, jnp.ones((x.shape[0], 1), x.dtype)], axis=1)
        wsum = jnp.maximum(jnp.sum(w), 1e-12)

        if multinomial:
            yoh = jax.nn.one_hot(y.astype(jnp.int32), n_classes,
                                 dtype=jnp.float32)

            def block_loss(B):
                logits = (sparse_ops.matmat(x, B) if sparse_blk
                          else x @ B)  # (n_blk, K)
                lse = jax.scipy.special.logsumexp(logits, axis=1)
                return jnp.sum(
                    w * (lse - jnp.sum(yoh * logits, axis=1))) / wsum
        else:
            def block_loss(b):
                eta = (sparse_ops.matvec(x, b) if sparse_blk
                       else x @ b)
                return jnp.sum(w * loss_fn(eta, y)) / wsum

        g = jax.grad(block_loss)(beta)
        lr = eta0 / (1.0 + t) ** power_t
        cand = beta - lr * g
        prox = pen_prox(cand, lr * lamduh)
        if fit_intercept:
            # prox only the penalized coordinates; intercept takes the plain
            # gradient step (unpenalized, matching the batch solvers' mask)
            cand = cand.at[:-1].set(prox[:-1])
        else:
            cand = prox
        return (cand, t + 1.0)

    return step


# One (step, jitted single-block apply) pair per hyperparameter config:
# stable identities keep both the single-step jit cache (host-loop
# partial_fit) and incremental_scan's per-step-fn compiled-scan cache warm
# across estimator instances and deepcopies.
_STREAM_CACHE: dict = {}


def get_stream_step(family="logistic", regularizer="l2", lamduh=0.0,
                    eta0=0.1, power_t=0.5, fit_intercept=True,
                    n_classes=None):
    """Cached :func:`make_sgd_step` plus a jitted one-block apply."""
    key = (family, regularizer, float(lamduh), float(eta0), float(power_t),
           bool(fit_intercept),
           None if n_classes is None else int(n_classes))
    if key not in _STREAM_CACHE:
        step = make_sgd_step(family=family, regularizer=regularizer,
                             lamduh=lamduh, eta0=eta0, power_t=power_t,
                             fit_intercept=fit_intercept,
                             n_classes=n_classes)
        apply_one = jax.jit(lambda s, x, y, w: step(s, (x, y, w)))
        _STREAM_CACHE[key] = (step, apply_one)
    return _STREAM_CACHE[key]


def make_batched_sgd_epoch(family="logistic", regularizer="l2",
                           fit_intercept=True):
    """Build the batched-candidate streaming epoch for asynchronous
    search rungs (model_selection/_incremental.py): M hyperparameter
    members advance through ONE data epoch as ONE jitted program.

    :func:`make_sgd_step` bakes ``lamduh``/``eta0``/``power_t`` into the
    step as Python closure constants — one compiled program PER
    hyperparameter point, which is exactly the compile storm an
    asynchronous search must not pay as rungs shrink. Here they are
    TRACED (M,) vectors and the per-member update is a vmap of the same
    proximal-SGD math, so every candidate of a bracket shares one
    executable for the whole search:

    ``epoch(betas, ts, lam, eta0, power_t, live, Xb, yb, wb, order)``
    scans the (B, bs, width) block stack in the traced ``order``
    permutation (a different seeded epoch order never recompiles) and
    returns updated ``(betas, ts)``. ``Xb`` arrives WITH the intercept
    ones-column already appended (the stack is built once per fit, so
    the per-step append of :func:`make_sgd_step` would be waste);
    ``live`` freezes stopped candidates — a promotion that shrinks the
    rung changes the mask, never a shape, which is what keeps later
    rungs at zero fresh compiles. Member outputs depend only on that
    member's (state, hyperparameters) and the shared blocks, so any
    host of an elastic roster recomputing a member reproduces its bytes
    exactly (the purity the re-deal protocol rides on).
    """
    loss_fn, _ = FAMILIES[family]
    _, pen_prox = _penalty(regularizer)

    def member_step(beta, t, lam, eta0, power_t, x, y, w):
        wsum = jnp.maximum(jnp.sum(w), 1e-12)

        def block_loss(b):
            return jnp.sum(w * loss_fn(x @ b, y)) / wsum

        g = jax.grad(block_loss)(beta)
        lr = eta0 / (1.0 + t) ** power_t
        cand = beta - lr * g
        prox = pen_prox(cand, lr * lam)
        if fit_intercept:
            cand = cand.at[:-1].set(prox[:-1])
        else:
            cand = prox
        return cand, t + 1.0

    vstep = jax.vmap(member_step,
                     in_axes=(0, 0, 0, 0, 0, None, None, None))

    def epoch(betas, ts, lam, eta0, power_t, live, Xb, yb, wb, order):
        def body(carry, b):
            bs, ts_ = carry
            nb, nt = vstep(bs, ts_, lam, eta0, power_t,
                           Xb[b], yb[b], wb[b])
            bs = jnp.where(live[:, None], nb, bs)
            ts_ = jnp.where(live, nt, ts_)
            return (bs, ts_), None

        (betas, ts), _ = jax.lax.scan(body, (betas, ts), order)
        return betas, ts

    return jax.jit(epoch)


# One compiled batched epoch per (family, regularizer, fit_intercept):
# stable identity keeps the jit cache warm across searches and resumes.
_BATCHED_STREAM_CACHE: dict = {}


def get_batched_sgd_epoch(family="logistic", regularizer="l2",
                          fit_intercept=True):
    """Cached :func:`make_batched_sgd_epoch`."""
    key = (family, regularizer, bool(fit_intercept))
    if key not in _BATCHED_STREAM_CACHE:
        _BATCHED_STREAM_CACHE[key] = make_batched_sgd_epoch(
            family=family, regularizer=regularizer,
            fit_intercept=fit_intercept)
    return _BATCHED_STREAM_CACHE[key]


SOLVERS = ("admm", "gradient_descent", "newton", "lbfgs", "proximal_grad")


def solve(solver, X, y, w, beta0, mask, mesh=None, **kwargs):
    """Solver dispatch — the analogue of ``dask_glm.algorithms._solvers``
    (reference: linear_model/glm.py:157)."""
    if solver not in SOLVERS:
        raise ValueError(
            f"'solver' must be one of {set(SOLVERS)}. Got {solver!r} instead"
        )
    if solver == "admm":
        if mesh is None:
            raise ValueError("admm requires a mesh")
        return admm(X, y, w, beta0, mask, mesh, **kwargs)
    table = {
        "gradient_descent": gradient_descent,
        "newton": newton,
        "lbfgs": lbfgs,
        "proximal_grad": proximal_grad,
    }
    return table[solver](X, y, w, beta0, mask, **kwargs)
