"""Pure-functional model cores.

Each model is a set of jitted pure functions (``init → state``,
``step(state, data) → state``, ``predict(state, data)``), SPMD over the mesh.
The estimator classes in the public subpackages (:mod:`dask_ml_tpu.cluster`,
:mod:`dask_ml_tpu.linear_model`, ...) are thin stateful shells over these, so
the compute path stays functional and compiler-friendly.
"""
