"""KMeans functional core: jitted Lloyd iterations + k-means|| initialization.

TPU-native rebuild of the reference's distributed KMeans
(reference: cluster/k_means.py — Lloyd loop ``_kmeans_single_lloyd:457-510``,
scalable init ``init_scalable:357-422``). Design mapping:

- The reference executes one dask graph per Lloyd iteration: per-block
  sklearn distance kernels (k_means.py:470-472), a Cython partial-centroid-sum
  kernel composed with ``da.atop`` (k_means.py:477-488, _k_means.pyx:29-78),
  a delayed tree-sum, and a driver-side convergence check (k_means.py:493-499).
- Here one Lloyd iteration is a single fused XLA program over the sharded
  data: distances are an ``X @ centersᵀ`` matmul on the MXU with a fused
  argmin epilogue, and the M-step is a weighted one-hot matmul
  (``onehotᵀ @ X`` — the TPU-native replacement for the Cython segment-sum;
  for small k a k×d matmul beats scatter-adds on the MXU). Cross-shard
  reduction is an XLA ``psum`` over the ICI, inserted automatically when the
  sharded sample axis is contracted. The convergence check runs on-device
  inside a ``lax.while_loop``, so a full ``fit`` is ONE XLA program with no
  per-iteration host round-trip (the reference pays a driver↔cluster barrier
  every iteration).

Padding rows carry weight 0 and therefore contribute nothing to sums, counts,
or inertia.
"""

from __future__ import annotations

import logging
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from dask_ml_tpu.ops.fused_distance import (
    _row_sumsq,
    fused_argmin_min,
    fused_argmin_min2,
    fused_argmin_min_sketched,
    fused_argmin_weight,
    fused_rowwise_min,
    row_block_evaluated,
)

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# Lloyd iterations
# ---------------------------------------------------------------------------


def _assign(X, w, centers):
    """Fused assignment: labels, weighted min-distances, inertia — routed
    through the fused distance-reduction family (ops/fused_distance.py),
    the single implementation of the distance+reduce idiom."""
    labels, mind = fused_argmin_min(X, centers)
    inertia = jnp.sum(mind * w)
    return labels, mind, inertia


def _new_centers(sums, counts, centers, live=None):
    """THE M-step finalization — the single source of truth for the
    divide/empty-cluster rule shared by every Lloyd implementation
    (plain, fused shard_map, batched-candidate). Counts are *weighted*
    sums and may legitimately be in (0, 1); only exact zeros are empty
    clusters, which keep their old center instead of collapsing to zero.
    ``live`` optionally restricts the update to a subset of rows (the
    batched path's ``k``-validity mask)."""
    occupied = counts > 0 if live is None else jnp.logical_and(
        live, counts > 0)
    safe = jnp.where(counts > 0, counts, 1.0)
    return jnp.where(occupied[:, None], sums / safe[:, None], centers)


def _m_step(X, w, labels, centers):
    """Weighted one-hot-matmul M-step (the Cython ``_centers_dense``
    replacement, reference: _k_means.pyx:29-78)."""
    k = centers.shape[0]
    onehot = jax.nn.one_hot(labels, k, dtype=X.dtype) * w[:, None]
    sums = onehot.T @ X  # (k, d): contraction over the sharded axis → psum
    counts = jnp.sum(onehot, axis=0)
    return _new_centers(sums, counts, centers), counts


@jax.jit
def lloyd_step(X, w, centers):
    """One Lloyd iteration. Returns (new_centers, labels, inertia, shift)."""
    labels, _, inertia = _assign(X, w, centers)
    new_centers, _ = _m_step(X, w, labels, centers)
    shift = jnp.sum((new_centers - centers) ** 2)
    return new_centers, labels, inertia, shift


@partial(jax.jit, static_argnames=("max_iter",))
def lloyd_loop(X, w, centers, tol, max_iter: int):
    """Full Lloyd optimization as one on-device ``lax.while_loop`` — the
    REPLICATED-array path, for small problems that fit one device: the
    k-means|| finishing pass over the candidate buffer
    (:func:`_init_scalable_device`) and the compile-check entrypoint. Large
    sharded fits go through :func:`lloyd_loop_fused`; both share the single
    M-step finalization :func:`_new_centers`, so the math cannot diverge.

    Returns (centers, inertia, n_iter, shift). The loop condition matches the
    reference's driver check ``shift < tol → stop``
    (reference: cluster/k_means.py:496-499) but never leaves the device.
    """

    def cond(state):
        _, _, it, shift = state
        return jnp.logical_and(it < max_iter, shift >= tol)

    def body(state):
        centers, _, it, _ = state
        new_centers, _, inertia, shift = lloyd_step(X, w, centers)
        return (new_centers, inertia.astype(jnp.float32), it + 1,
                shift.astype(jnp.float32))

    # centers carry in f32 regardless of the caller's dtype: the M-step's
    # f32-accumulated sums promote new_centers, and a bf16 init would
    # type-mismatch the while_loop carry (lloyd_loop_fused does the same)
    init = (centers.astype(jnp.float32), jnp.asarray(jnp.inf, jnp.float32),
            jnp.asarray(0, jnp.int32), jnp.asarray(jnp.inf, jnp.float32))
    return jax.lax.while_loop(cond, body, init)


_LLOYD_BLK = 2048  # lanes per pallas block; d·BLK·4B ≈ 0.4–2 MB of VMEM


def _pallas_lloyd_supported(k: int, d: int) -> bool:
    """Shapes the single-pass kernel handles with comfortable VMEM margins.
    Shapes beyond the bound are REJECTED for an explicit ``kernel='pallas'``
    request (ValueError at trace time); ``'auto'`` selects pallas only in
    its measured winning regimes — see :func:`_pallas_auto_wins`."""
    return k <= 128 and d <= 512


def _pallas_auto_wins(k: int, d: int, dtype) -> bool:
    """The regimes where the single-pass Pallas kernel MEASURED faster than
    the two-read XLA path on TPU (full sweep in the r4 notes; every cell
    below re-measured with runtimes ≫ the host-link RTT):

    ====  ====  ========  ==============
       d     k  dtype     pallas / xla
    ====  ====  ========  ==============
      50   128  f32       **6.8×**  (XLA's two-pass collapses at k=128)
      50   128  bf16      **7.8×**
     256     8  bf16      1.84×
     256    64  bf16      1.79×
     256   128  bf16      1.57×
     512     8  bf16      2.04×
     512   128  bf16      1.51×
      50    64  f32/bf16  1.1–1.2×  (parity band — XLA kept)
      50  8–96  f32       0.5–1.0×  (XLA wins; incl. the flagship shape)
     256+  any  f32       0.9–1.1×  (parity — XLA kept)
    ====  ====  ========  ==============

    Rule distilled from the table, conservative (pallas only where it won
    ≥1.5× reliably): large-k/small-d any dtype, or bf16 with d ≥ 128.
    TPU only — on other backends the kernel runs in interpret mode and the
    measurements do not transfer.

    The decision cache (``parallel/decisions.py``) is consulted first:
    where a bench run has TIMED this (k, d, dtype) regime on this backend,
    its verdict overrides the distilled rule; everywhere else the rule
    above is the cold-start fallback. The support bound stays outside the
    cache — it is a correctness guard, not a speed question."""
    if not _pallas_lloyd_supported(k, d):
        return False
    from dask_ml_tpu.parallel import decisions

    def _fallback():
        if jax.default_backend() != "tpu":
            return False
        if k >= 128 and d <= 128:
            return True
        return dtype == jnp.bfloat16 and d >= 128

    return decisions.lookup(
        "kmeans.lloyd.pallas",
        {"k": k, "d": d, "dtype": str(jnp.dtype(dtype))},
        fallback=_fallback())


def _lloyd_iter_pallas(centers, XT, w2d, n_loc: int):
    """ONE Lloyd iteration as a single pass over the shard's data.

    The XLA path reads X twice per iteration (distance matmul, then M-step
    matmul). This Pallas kernel streams feature-major blocks of X through
    VMEM once and does everything per block — distances on the MXU, argmin/
    one-hot on the VPU, and BOTH the (k, d) weighted-sum accumulation and
    the inertia reduction before the block leaves VMEM (VMEM-scratch
    accumulators, written to the outputs on the final sequential grid
    step). Halves the LOGICAL HBM traffic of the dominant loop.

    **Measured verdict (r4 regime sweep)**: on the flagship bench shape
    (1M×50, k=8, f32) the XLA two-read path runs each iteration at the
    full memory bandwidth of BOTH passes (~5.4B samples/s/chip — the
    hardware roofline for its traffic) and beats this kernel ~2×: halving
    logical traffic does not pay when Mosaic's pipeline can't saturate the
    HBM. But the full (d, k, dtype) sweep found regimes where the fusion
    WINS decisively — k=128 with small d (XLA's two-pass path collapses to
    ~235M samples/s there; this kernel sustains 1.6–1.9B, a 6.8–7.8×
    win) and bf16 inputs with d ≥ 128 (1.5–2×). ``kernel="auto"``
    dispatches on the measured rule (:func:`_pallas_auto_wins`).

    ``n_loc`` masks the final partial block (grid is ceil-div); padding
    rows inside ``n_loc`` are handled by their zero weights, as everywhere.
    """
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    k, d = centers.shape
    blk = _LLOYD_BLK
    n_pad = XT.shape[1]
    grid = (n_pad + blk - 1) // blk

    def kernel(c_ref, xt_ref, w_ref, sums_ref, counts_ref, inertia_ref,
               acc_s, acc_c, acc_i):
        j = pl.program_id(0)

        @pl.when(j == 0)
        def _():
            acc_s[:] = jnp.zeros_like(acc_s)
            acc_c[:] = jnp.zeros_like(acc_c)
            acc_i[:] = jnp.zeros_like(acc_i)

        C = c_ref[:]  # (k, d) f32
        col = j * blk + jax.lax.broadcasted_iota(jnp.int32, (1, blk), 1)
        valid = col < n_loc
        # Zero the final block's out-of-range columns with a SELECT: OOB
        # block contents are undefined (NaN in interpret mode), and
        # 0·NaN = NaN would survive a multiplicative mask and poison the
        # matmul contraction.
        Xb = jnp.where(valid, xt_ref[:], 0)  # (d, blk)
        wv = jnp.where(valid, w_ref[:], 0.0)  # (1, blk); padding rows w=0

        prod = jax.lax.dot_general(
            C.astype(Xb.dtype), Xb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)  # (k, blk) on the MXU
        c2 = jnp.sum(C * C, axis=1, keepdims=True)  # (k, 1)
        scores = c2 - 2.0 * prod
        best = jnp.argmin(scores, axis=0, keepdims=True)  # (1, blk)
        kiota = jax.lax.broadcasted_iota(jnp.int32, (k, blk), 0)
        oh_w = (kiota == best).astype(jnp.float32) * wv  # (k, blk)

        # accumulate in VMEM SCRATCH (not the output refs): revisited
        # output blocks can be written back per grid step, serializing the
        # loop on tiny DMAs — scratch stays resident, outputs are written
        # once on the final step
        acc_s[:] += jax.lax.dot_general(
            oh_w, Xb.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # (k, d) on the MXU
        acc_c[:] += jnp.sum(oh_w, axis=1, keepdims=True)  # (k, 1)
        # inertia needs ‖x‖², computed from the block already in VMEM
        x2b = jnp.sum(
            Xb.astype(jnp.float32) * Xb.astype(jnp.float32),
            axis=0, keepdims=True)  # (1, blk)
        mind = jnp.maximum(jnp.min(scores, axis=0, keepdims=True) + x2b, 0.0)
        # keep the store 2-D: Mosaic rejects scalar stores to VMEM refs
        acc_i[:] += jnp.sum(mind * wv, axis=(0, 1), keepdims=True)

        @pl.when(j == grid - 1)
        def _():
            sums_ref[:] = acc_s[:]
            counts_ref[:] = acc_c[:]
            inertia_ref[:] = acc_i[:]

    return pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((k, d), lambda j: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((d, blk), lambda j: (0, j), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, blk), lambda j: (0, j), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((k, d), lambda j: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((k, 1), lambda j: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda j: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((k, d), jnp.float32),
            jax.ShapeDtypeStruct((k, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((k, d), jnp.float32),
            pltpu.VMEM((k, 1), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
        ],
        interpret=jax.default_backend() != "tpu",
    )(centers, XT, w2d)


@partial(jax.jit, static_argnames=("mesh", "max_iter", "kernel",
                                   "shard_features"))
def lloyd_loop_fused(X, w, centers0, tol, *, mesh, max_iter: int,
                     kernel: str = "auto", shard_features: bool = False):
    """Bandwidth-optimal Lloyd over a feature-major (transposed) copy of X.

    Two layout/scheduling facts dominate this kernel's speed on TPU, both
    found by measurement (see bench.py for the methodology):

    1. **Lane padding.** TPU tiles are (sublane, 128-lane); an (n, d) array
       with small d (the reference workload has d=50) is physically padded
       d→128 in the minor dimension, so every pass over X reads up to 2.56×
       the logical bytes. Transposing once to (d, n) moves the padding to the
       sublane dimension (50→56 for f32), making physical ≈ logical traffic.
       The transpose costs one extra pass, amortized over all Lloyd
       iterations.
    2. **Let XLA tile.** Handing the whole shard to XLA as plain matmul +
       elementwise ops beats a hand-written `lax.scan` over VMEM-sized
       blocks: XLA's own pipelined tiling overlaps HBM reads with compute,
       while a scan serializes them. (A previous revision of this kernel
       scanned manually and also collapsed to pathological block sizes when
       the per-shard row count was prime; both problems are gone.)

    Per iteration each shard computes distances as one (k, n_loc) matmul on
    the MXU with a fused argmin/one-hot/M-step epilogue — the TPU-native
    replacement for the reference's per-block Cython segment-sum + dask
    tree-reduce (reference: cluster/k_means.py:470-492, _k_means.pyx:29-78).
    The per-row ‖x‖² term is loop-invariant and hoisted out of the while_loop
    (only the ``-2·x·c + ‖c‖²`` part enters the argmin; inertia adds ‖x‖²
    back). Cross-shard reduction is one psum of (k·d + k + 1) floats per
    iteration over the ICI, and the convergence check stays on device, so the
    entire optimization is a single XLA program with no per-iteration host
    round-trip (the reference pays a driver↔cluster barrier every iteration).

    Accepts bf16 or f32 X; distances, sums, counts and inertia always
    accumulate in f32 (``preferred_element_type``). On bandwidth-bound shapes
    f32 is typically *faster* end-to-end than bf16 here, because Mosaic's
    small-d bf16 matmul tiling is less efficient — measure before switching.

    ``kernel`` selects the per-iteration implementation: ``"xla"`` is the
    two-matmul whole-shard path above; ``"pallas"`` is the single-pass
    kernel (:func:`_lloyd_iter_pallas`) that halves per-iteration logical
    HBM traffic by fusing the M-step accumulation into the distance pass.
    ``"auto"`` (default) picks per the MEASURED winning-regime rule
    (:func:`_pallas_auto_wins`): pallas for k=128-class problems with
    small d (6.8–7.8× there) and for bf16 with d ≥ 128 (1.5–2×); XLA
    everywhere else, including the flagship small-k f32 shape where its
    two-pass roofline wins.

    On a hierarchical ``('pod', 'chip')`` mesh the per-iteration M-step
    reduction lowers as reduce-within-pod (ICI) then across pods (DCN)
    through :func:`~dask_ml_tpu.parallel.hierarchy.hpsum` — only one
    already-reduced (k·d + k + 1)-float partial per pod crosses the DCN
    per iteration, with per-axis bytes metered in the traffic ledger
    (docs/scale-out.md). On a flat mesh the same call IS today's single
    psum over ``"data"`` — bit-identical program.

    ``shard_features=True`` on a mesh with a ``model`` axis runs the
    FEATURE-PARALLEL variant (docs/scale-out.md "The model axis"): X
    enters sharded over both axes (``P(data_axes, 'model')``), centers
    carry and return as ``P(None, 'model')`` column slices — per-chip
    center state is (k, d/m), which is what lets k·d grow past one chip's
    HBM. Each iteration's partial scores reduce over 'model'
    (``mpsum``, op ``kmeans.scores``); the argmin, counts and inertia are
    then model-invariant, and the M-step sums stay feature-local so the
    (pod, chip) ``hpsum`` moves only (k·d/m + k + 1) floats per chip —
    the model axis SHRINKS the sample-axis traffic m-fold. The pallas
    kernel's accumulator layout is d-global, so the feature-parallel
    variant is XLA-only (an explicit ``kernel='pallas'`` raises; 'auto'
    never selects it here). With ``model=1`` (or a model-less mesh) the
    flag is inert and the program is the 2-axis one, bit-identical.
    """
    from jax.sharding import PartitionSpec as P

    from dask_ml_tpu.parallel.hierarchy import hpsum, mpsum
    from dask_ml_tpu.parallel.mesh import (MODEL_AXIS, data_pspec,
                                           feature_pspec, n_model_shards,
                                           shard_map)

    k, d = centers0.shape
    if kernel not in ("auto", "pallas", "xla"):
        raise ValueError(f"kernel must be auto|pallas|xla, got {kernel!r}")
    if kernel == "pallas" and not _pallas_lloyd_supported(k, d):
        raise ValueError(
            f"kernel='pallas' supports k<=128, d<=512; got k={k}, d={d}")
    model = bool(shard_features) and n_model_shards(mesh) > 1
    if model and kernel == "pallas":
        raise ValueError(
            "kernel='pallas' does not compose with feature sharding "
            "(the single-pass kernel accumulates d-global state); use "
            "kernel='xla' or 'auto'")
    use_pallas = not model and (kernel == "pallas" or (
        kernel == "auto" and _pallas_auto_wins(k, d, X.dtype)))

    dspec2, dspec1 = data_pspec(mesh, ndim=2), data_pspec(mesh, ndim=1)
    if model:
        dspec2 = feature_pspec(mesh, ndim=2)
    cspec = P(None, MODEL_AXIS) if model else P()

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(dspec2, dspec1, cspec, P()),
        out_specs=(cspec, P(), P(), P()),
        # vma typing can't see through a pallas_call (and interpret mode
        # trips on kernel-internal constants), so the pallas path runs
        # unchecked; the default XLA path keeps the check.
        check_vma=not use_pallas,
    )
    def run(X_loc, w_loc, c0, tol_):
        # One-time feature-major relayout; the barrier keeps XLA from fusing
        # the transpose into each iteration's reads (which would re-pad d
        # back onto the lane dimension).
        XT = jax.lax.optimization_barrier(X_loc.T)  # (d[/m], n_loc)
        if use_pallas:
            w2d = w_loc[None, :].astype(jnp.float32)
        else:
            x2 = jnp.sum(XT.astype(jnp.float32) ** 2, axis=0)  # invariant
            if model:
                # ‖x‖² needs every feature: one loop-hoisted model psum
                x2 = mpsum(x2, mesh, op="kmeans.x2")
            kidx = jnp.arange(k, dtype=jnp.int32)[:, None]

        def local_stats_xla(centers):
            cx = centers.astype(XT.dtype)
            c2 = jnp.sum(centers * centers, axis=1)  # (k,) f32 [partial]
            prod = jax.lax.dot_general(
                cx, XT, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)  # (k, n_loc)
            scores = c2[:, None] - 2.0 * prod
            if model:
                # feature-partial scores combine over 'model'; everything
                # derived from them (argmin, counts, inertia) is then
                # model-invariant by construction
                scores = mpsum(scores, mesh, op="kmeans.scores")
            best = jnp.argmin(scores, axis=0).astype(jnp.int32)
            onehot = (kidx == best[None, :]).astype(jnp.float32)
            oh_w = onehot * w_loc[None, :]
            sums = jax.lax.dot_general(
                oh_w, XT.astype(jnp.float32), (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)  # (k, d[/m])
            counts = oh_w.sum(axis=1)
            mind = jnp.maximum(jnp.min(scores, axis=0) + x2, 0.0)
            inertia = jnp.sum(mind * w_loc)
            return sums, counts, inertia

        def local_stats_pallas(centers):
            sums, counts2d, inert = _lloyd_iter_pallas(
                centers, XT, w2d, int(XT.shape[1]))
            return sums, counts2d[:, 0], inert[0, 0]

        local_stats = local_stats_pallas if use_pallas else local_stats_xla

        def one_iter(centers):
            sums, counts, inertia = local_stats(centers)
            sums = hpsum(sums, mesh, op="kmeans.mstep")
            counts = hpsum(counts, mesh, op="kmeans.mstep")
            inertia = hpsum(inertia, mesh, op="kmeans.mstep")
            new_centers = _new_centers(sums, counts, centers)
            shift = jnp.sum((new_centers - centers) ** 2)
            if model:
                # per-slice partial shift → global shift, so the model
                # shards agree on the convergence decision exactly
                shift = mpsum(shift, mesh, op="kmeans.shift")
            return new_centers, inertia, shift

        def cond(state):
            _, _, it, shift = state
            return jnp.logical_and(it < max_iter, shift >= tol_)

        def body(state):
            centers, _, it, _ = state
            new_centers, inertia, shift = one_iter(centers)
            return new_centers, inertia, it + 1, shift

        init = (c0.astype(jnp.float32),
                jnp.asarray(jnp.inf, jnp.float32),
                jnp.asarray(0, jnp.int32),
                jnp.asarray(jnp.inf, jnp.float32))
        return jax.lax.while_loop(cond, body, init)

    return run(X, w, centers0.astype(jnp.float32),
               jnp.asarray(tol, jnp.float32))


# ---------------------------------------------------------------------------
# Bound-based Lloyd: skip distance work with Elkan/Yinyang center-movement
# bounds (arxiv 2105.02936, arxiv 1605.02989; ROADMAP item 3)
# ---------------------------------------------------------------------------

#: relative inflation applied to every bound-side quantity (seeds and
#: movement decrements). The bounds' validity argument is exact-arithmetic
#: triangle inequality; the slack absorbs f32 rounding of the sqrt and
#: the movement norms, so a row is only ever skipped when its margin
#: exceeds FP noise by ~two orders of magnitude — a skipped row's
#: assignment provably cannot change even under the oracle's own rounded
#: scores. Near-exact ties (margin below the slack) always re-evaluate
#: and inherit the oracle's lowest-index convention.
_BOUND_SLACK = 1e-5

#: ABSOLUTE slack on the seeded squared distances, scaled by the operand
#: magnitudes ``|x|² + max|c|²``: the ``|c|² − 2x·c + |x|²`` expression
#: cancels catastrophically when the distance is far smaller than the
#: operands, so its f32 error is relative to the NORMS, not the distance
#: — a purely relative slack under-covers exactly the near-center rows
#: the bounds most want to skip. 1e-5 ≈ 84·eps_f32 of headroom.
_BOUND_EPS_ABS = 1e-5

#: while_loop carry layout version of the bounded Lloyd loop
#: (``lloyd_bounded_resumable`` binds it into every snapshot; a resume
#: against a snapshot written by a different layout is a loud error,
#: never a silently mis-shaped carry). Bump on ANY carry change.
BOUNDED_CARRY_VERSION = 1


def _bounded_auto_wins(n: int, k: int, d: int) -> bool:
    """The regimes where ``algorithm='auto'`` selects the bounded loop.

    The bound machinery pays O(n·(G+1)) state plus per-iteration bound
    updates to skip the O(n·k·d) assignment pass; the skip only
    amortizes once n is large enough that the assignment pass dominates
    the loop and k is large enough that a skipped row saves real work
    (k ≥ 4 — below that the assignment pass is already cheaper than the
    M-step it cannot skip). Small problems keep the plain fused loop:
    the bench trajectory (BOUNDS_r01.json) measures the crossover; this
    rule is deliberately conservative so 'auto' never loses. Bench-timed
    regimes in the decision cache (``parallel/decisions.py``) override
    the rule point-wise; it remains the cold-start fallback."""
    from dask_ml_tpu.parallel import decisions

    return decisions.lookup(
        "kmeans.lloyd.bounded", {"n": n, "k": k, "d": d},
        fallback=n >= (1 << 16) and k >= 4)


def _bounded_groups(k: int, groups):
    """(G, size) for the Yinyang center grouping: ``groups='auto'``
    follows the Yinyang paper's t = ⌈k/10⌉ (one group — pure
    Hamerly-style single lower bound — until k reaches double digits),
    an int clips to [1, k]. Centers are grouped by contiguous index
    (``gid = arange(k) // size``): center identity is stable across
    iterations (the M-step never permutes rows), so no re-grouping is
    ever needed and the carry stays O(n·G)."""
    if groups == "auto":
        G = max(1, -(-k // 10))
    else:
        G = max(1, min(int(groups), k))
    size = -(-k // G)
    return -(-k // size), size


def _bounded_need(ub, lb, w_pos, *, prune: bool):
    """The Yinyang global filter: a row needs distance work only when its
    upper bound fails to clear the tightest group lower bound. Strict
    inequality — at equality the true distances may tie, and ties are the
    oracle's (lowest index) to break, so the row re-evaluates."""
    if not prune:
        return w_pos
    return jnp.logical_and(w_pos, ub >= jnp.min(lb, axis=1))


def _bounded_assign(X_pad, x2_pad, centers, labels, ub, lb, w_pos, *,
                    kernel: str, prune: bool, bdt):
    """One bounded assignment step: evaluate the rows the bounds cannot
    clear (block-wise through :func:`fused_argmin_min2`), overlay carried
    labels/bounds for skipped blocks, and reseed bounds for evaluated
    rows (upper = best distance, every group lower = global second-best —
    a valid lower bound for each group's non-assigned minimum at once).
    The seeds carry the magnitude-scaled absolute slack
    (:data:`_BOUND_EPS_ABS` — the computed squared distances cancel
    against ``|x|² + |c|²``, so their f32 error scales with the norms).
    ``x2_pad`` is the hoisted per-row ``Σx²``. Returns
    (labels, ub, lb, n_rows_skipped, n_bounds_held)."""
    s = _BOUND_SLACK
    need = _bounded_need(ub, lb, w_pos, prune=prune)
    idx, d1, d2 = fused_argmin_min2(X_pad, centers, row_need=need,
                                    kernel=kernel)
    ev = row_block_evaluated(need)
    labels = jnp.where(ev, idx, labels)
    c2max = jnp.max(jnp.sum(centers * centers, axis=1))
    slack_sq = _BOUND_EPS_ABS * (x2_pad + c2max)
    ub = jnp.where(ev, (jnp.sqrt(d1 + slack_sq) * (1 + s)).astype(bdt), ub)
    lb_seed = (jnp.sqrt(jnp.maximum(d2 - slack_sq, 0.0)) * (1 - s)
               ).astype(bdt)
    lb = jnp.where(ev[:, None], lb_seed[:, None], lb)
    skipped = jnp.sum(jnp.logical_and(w_pos, jnp.logical_not(ev))
                      .astype(jnp.int32))
    held = jnp.sum(jnp.logical_and(w_pos, jnp.logical_not(need))
                   .astype(jnp.int32))
    return labels, ub, lb, skipped, held


def _bounded_move(ub, lb, labels, centers, new_centers, gid, G, bdt):
    """Center-movement bound maintenance: the upper bound drifts up by the
    assigned center's movement, each group lower bound drifts down by its
    group's LARGEST movement (the whole point of Yinyang groups — one
    decrement per group instead of the global max). Movements are
    inflated by the slack so FP-rounded norms can never under-account a
    real move."""
    delta = (jnp.sqrt(jnp.sum((new_centers - centers) ** 2, axis=1))
             .astype(bdt)) * (1 + _BOUND_SLACK)
    dg = jnp.zeros((G,), bdt).at[gid].max(delta)
    return ub + delta[labels], lb - dg[None, :]


def _bounded_init_state(centers0, n_pad: int, G: int, max_iter: int, bdt):
    """Zero'd carry: zero bounds force a full evaluation on iteration 0
    (``ub >= min(lb)`` holds at 0 ≥ 0), which seeds everything."""
    return (centers0.astype(jnp.float32),
            jnp.zeros((n_pad,), jnp.int32),
            jnp.zeros((n_pad,), bdt),
            jnp.zeros((n_pad, G), bdt),
            jnp.asarray(0, jnp.int32),
            jnp.asarray(jnp.inf, jnp.float32),
            jnp.zeros((max_iter,), jnp.int32),
            jnp.zeros((max_iter,), jnp.int32))


def _pad_rows_to_blocks(X, w):
    """Zero-row/zero-weight padding up to the family's skip-block quantum,
    done ONCE before the while_loop (a per-iteration pad would re-copy X
    every step). Zero-weight rows are inert everywhere by the package-wide
    padding contract."""
    from dask_ml_tpu.ops.fused_distance import _row_blocks

    n = X.shape[0]
    _, n_pad = _row_blocks(n)
    if n_pad == n:
        return X, w
    return (jnp.pad(X, ((0, n_pad - n), (0, 0))),
            jnp.pad(w, (0, n_pad - n)))


@partial(jax.jit, static_argnames=("mesh", "max_iter", "kernel", "groups",
                                   "prune", "bounds_dtype"))
def lloyd_loop_bounded(X, w, centers0, tol, *, max_iter: int, mesh=None,
                       kernel: str = "auto", groups="auto",
                       prune: bool = True, bounds_dtype=jnp.float32):
    """Lloyd optimization that SKIPS distance work via Elkan/Yinyang
    center-movement bounds — the existing loops are the bit-compatible
    oracles (``lloyd_loop`` replicated, ``lloyd_loop_fused`` sharded).

    The carry extends the oracle's (centers, it, shift) with O(n·(G+1))
    bound state in ``bounds_dtype`` (≥ f32 via the precision policy's
    :func:`~dask_ml_tpu.parallel.precision.lloyd_bounds_dtype` — 8
    mantissa bits cannot hold a bound that must out-resolve FP noise on
    distances):

    - ``labels (n,) int32`` — each row's current assignment,
    - ``ub (n,)`` — upper bound on the distance (NOT squared: the
      triangle inequality lives in metric space) to the assigned center,
    - ``lb (n, G)`` — per-group lower bounds on the distance to the
      nearest NON-assigned center of each Yinyang group.

    Per iteration: rows with ``ub < min_g lb_g`` provably keep their
    assignment and skip the distance pass BLOCK-wise (the family's
    ``row_need`` contract — XLA blocks genuinely don't execute via
    ``lax.map``+``cond``, pallas blocks skip under ``pl.when``);
    everyone else re-evaluates through :func:`fused_argmin_min2`, whose
    best/second-best distances reseed ub and every group's lb. The
    M-step then runs over ALL rows from the (exact) labels with the
    ORACLE'S OWN expression — ``_m_step`` on the replicated path, the
    ``lloyd_loop_fused`` one-hot/XT contraction + psum on the mesh path
    — so center trajectories, shifts, and the stopping iteration are
    bit-identical to the unpruned loop: pruning only removes distance
    work whose outcome the bounds already prove. Finally each center's
    movement inflates ub and deflates its group's lb (by the group max),
    keeping both valid without touching the data.

    Returns ``(centers, inertia, n_iter, shift, labels, stats)``:
    inertia and labels come from one full assignment pass against the
    RETURNED centers (the estimator's post-loop re-assignment contract —
    the loop itself never knows skipped rows' exact distances), and
    ``stats`` carries ``rows_skipped``/``bounds_held`` per-iteration
    int32 arrays of length ``max_iter`` (entries beyond ``n_iter`` are
    zero) — ``rows_skipped`` counts rows whose distance work was
    actually avoided (block granularity), ``bounds_held`` counts rows
    whose bound held (row granularity, ≥ the block-wise number).
    """
    k, d = centers0.shape
    G, size = _bounded_groups(k, groups)
    gid = jnp.arange(k, dtype=jnp.int32) // size
    if kernel not in ("auto", "pallas", "xla"):
        raise ValueError(f"kernel must be auto|pallas|xla, got {kernel!r}")

    if mesh is None:
        X_pad, w_pad = _pad_rows_to_blocks(X, w)
        n_pad = X_pad.shape[0]
        w_pos = w_pad > 0
        x2_pad = jnp.sum(X_pad.astype(jnp.float32) ** 2, axis=1)  # invariant
        bdt = jnp.dtype(bounds_dtype)

        def cond(state):
            _, _, _, _, it, shift, _, _ = state
            return jnp.logical_and(it < max_iter, shift >= tol)

        def body(state):
            centers, labels, ub, lb, it, _, skip_h, held_h = state
            labels, ub, lb, skipped, held = _bounded_assign(
                X_pad, x2_pad, centers, labels, ub, lb, w_pos,
                kernel=kernel, prune=prune, bdt=bdt)
            # the ORACLE'S M-step expression over the ORIGINAL (un-block-
            # padded) rows: identical reduction lengths → identical bits
            new_centers, _ = _m_step(X, w, labels[:X.shape[0]], centers)
            shift = jnp.sum((new_centers - centers) ** 2)
            ub, lb = _bounded_move(ub, lb, labels, centers, new_centers,
                                   gid, G, bdt)
            skip_h = skip_h.at[it].set(skipped)
            held_h = held_h.at[it].set(held)
            return (new_centers, labels, ub, lb, it + 1,
                    shift.astype(jnp.float32), skip_h, held_h)

        state = jax.lax.while_loop(
            cond, body, _bounded_init_state(centers0, n_pad, G, max_iter,
                                            bdt))
        centers, _, _, _, n_iter, shift, skip_h, held_h = state
        labels_f, mind_f = fused_argmin_min(X, centers, kernel=kernel)
        inertia = jnp.sum(mind_f * w)
        return (centers, inertia, n_iter, shift, labels_f,
                {"rows_skipped": skip_h, "bounds_held": held_h})

    # ---- sharded path: the lloyd_loop_fused counterpart -----------------
    from jax.sharding import PartitionSpec as P

    from dask_ml_tpu.parallel.hierarchy import hpsum
    from dask_ml_tpu.parallel.mesh import data_pspec, shard_map

    bdt = jnp.dtype(bounds_dtype)
    kidx = jnp.arange(k, dtype=jnp.int32)[:, None]
    dspec2, dspec1 = data_pspec(mesh, ndim=2), data_pspec(mesh, ndim=1)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(dspec2, dspec1, P(), P()),
        out_specs=(P(), P(), P(), P(), dspec1, P()),
        # the row-skipping eval runs lax.cond/pallas inside — vma typing
        # can't see through either (same rule as the fused family's own
        # shard_map wrappers)
        check_vma=False,
    )
    def run(X_loc, w_loc, c0, tol_):
        n_loc = X_loc.shape[0]
        X_pad, w_pad = _pad_rows_to_blocks(X_loc, w_loc)
        w_pos = w_pad > 0
        x2_pad = jnp.sum(X_pad.astype(jnp.float32) ** 2, axis=1)  # invariant
        # feature-major copy for the M-step — the lloyd_loop_fused layout
        # (lane padding off the minor dim); the assignment blocks read the
        # row-major original, so both layouts stay resident for the loop
        XT = jax.lax.optimization_barrier(X_loc.T)  # (d, n_loc)

        def m_step(labels, centers):
            # VERBATIM lloyd_loop_fused local_stats M-step: same onehot,
            # same contraction, same psum order → bit-identical centers
            onehot = (kidx == labels[None, :n_loc]).astype(jnp.float32)
            oh_w = onehot * w_loc[None, :]
            sums = jax.lax.dot_general(
                oh_w, XT.astype(jnp.float32), (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)  # (k, d)
            counts = oh_w.sum(axis=1)
            # the bounded carry's movement norms (_bounded_move) derive
            # from these reduced centers, so the M-step psum is the one
            # collective the whole bound machinery rides on
            sums = hpsum(sums, mesh, op="kmeans.mstep")
            counts = hpsum(counts, mesh, op="kmeans.mstep")
            return _new_centers(sums, counts, centers)

        def cond(state):
            _, _, _, _, it, shift, _, _ = state
            return jnp.logical_and(it < max_iter, shift >= tol_)

        def body(state):
            centers, labels, ub, lb, it, _, skip_h, held_h = state
            labels, ub, lb, skipped, held = _bounded_assign(
                X_pad, x2_pad, centers, labels, ub, lb, w_pos,
                kernel=kernel, prune=prune, bdt=bdt)
            new_centers = m_step(labels, centers)
            shift = jnp.sum((new_centers - centers) ** 2)
            ub, lb = _bounded_move(ub, lb, labels, centers, new_centers,
                                   gid, G, bdt)
            skip_h = skip_h.at[it].set(skipped)
            held_h = held_h.at[it].set(held)
            return (new_centers, labels, ub, lb, it + 1,
                    shift.astype(jnp.float32), skip_h, held_h)

        state = jax.lax.while_loop(
            cond, body,
            _bounded_init_state(c0, X_pad.shape[0], G, max_iter, bdt))
        centers, _, _, _, n_iter, shift, skip_h, held_h = state
        labels_f, mind_f = fused_argmin_min(X_loc, centers, kernel=kernel)
        inertia = hpsum(jnp.sum(mind_f * w_loc), mesh, op="kmeans.inertia")
        stats = {"rows_skipped": hpsum(skip_h, mesh, op="kmeans.stats"),
                 "bounds_held": hpsum(held_h, mesh, op="kmeans.stats")}
        return centers, inertia, n_iter, shift, labels_f, stats

    return run(X, w, centers0.astype(jnp.float32),
               jnp.asarray(tol, jnp.float32))


@partial(jax.jit, static_argnames=("max_iter", "chunk", "kernel", "groups",
                                   "prune", "bounds_dtype"))
def _bounded_chunk(X, w, state, tol, *, max_iter: int, chunk: int,
                   kernel: str, groups, prune: bool, bounds_dtype):
    """Up to ``chunk`` bounded Lloyd iterations from a threaded carry —
    the resumable unit :func:`lloyd_bounded_resumable` drives. Same body
    and stopping rule as the replicated :func:`lloyd_loop_bounded`, with
    the extra per-chunk budget, so chunked execution composes to the
    exact same trajectory."""
    k = state[0].shape[0]
    G, size = _bounded_groups(k, groups)
    gid = jnp.arange(k, dtype=jnp.int32) // size
    bdt = jnp.dtype(bounds_dtype)
    X_pad, w_pad = _pad_rows_to_blocks(X, w)
    w_pos = w_pad > 0
    x2_pad = jnp.sum(X_pad.astype(jnp.float32) ** 2, axis=1)  # invariant
    it0 = state[4]

    def cond(st):
        _, _, _, _, it, shift, _, _ = st
        return jnp.logical_and(
            jnp.logical_and(it < max_iter, it - it0 < chunk), shift >= tol)

    def body(st):
        centers, labels, ub, lb, it, _, skip_h, held_h = st
        labels, ub, lb, skipped, held = _bounded_assign(
            X_pad, x2_pad, centers, labels, ub, lb, w_pos,
            kernel=kernel, prune=prune, bdt=bdt)
        new_centers, _ = _m_step(X, w, labels[:X.shape[0]], centers)
        shift = jnp.sum((new_centers - centers) ** 2)
        ub, lb = _bounded_move(ub, lb, labels, centers, new_centers,
                               gid, G, bdt)
        skip_h = skip_h.at[it].set(skipped)
        held_h = held_h.at[it].set(held)
        return (new_centers, labels, ub, lb, it + 1,
                shift.astype(jnp.float32), skip_h, held_h)

    return jax.lax.while_loop(cond, body, state)


@partial(jax.jit, static_argnames=("kernel",))
def _bounded_final_assign(X, w, centers, *, kernel: str):
    """The bounded loops' post-loop full assignment + inertia, as ONE
    jitted program. :func:`lloyd_bounded_resumable` must run this jitted,
    not eagerly: the one-shot :func:`lloyd_loop_bounded` compiles the
    identical expression inside its own program, and the eager op-by-op
    ``sum(mind * w)`` reduces in a different order — last-bit inertia
    drift that breaks the "same tuple as the one-shot" contract."""
    labels_f, mind_f = fused_argmin_min(X, centers, kernel=kernel)
    return labels_f, jnp.sum(mind_f * w)


def lloyd_bounded_resumable(X, w, centers0, tol, *, max_iter: int,
                            path: str, chunk_iters: int = 10,
                            every: int = 1, kernel: str = "auto",
                            groups="auto", prune: bool = True,
                            bounds_dtype=jnp.float32):
    """Preemption-safe bounded Lloyd: chunks of device iterations with the
    extended carry snapshotted through the :class:`ScanCheckpoint`
    machinery (parallel/faults.py) between chunks, so a killed fit
    resumes BIT-identically from the last snapshot — the bounds are part
    of the carry, so a resume neither loses pruning power nor re-derives
    stale bounds.

    The snapshot binds :data:`BOUNDED_CARRY_VERSION` plus the problem
    shape; loading a snapshot written by a different carry layout (or a
    different problem) is a loud error, never a silently mis-shaped
    carry. Returns the same tuple as the replicated
    :func:`lloyd_loop_bounded`; the snapshot is deleted on completion
    (the admm_streamed contract)."""
    from dask_ml_tpu.parallel.faults import ScanCheckpoint

    class _BoundedLloydCheckpoint(ScanCheckpoint):
        KIND = "lloyd_bounded"

    k, d = centers0.shape
    G, size = _bounded_groups(k, groups)
    gid = jnp.arange(k, dtype=jnp.int32) // size
    bdt = jnp.dtype(bounds_dtype)
    from dask_ml_tpu.ops.fused_distance import _row_blocks

    _, n_pad = _row_blocks(X.shape[0])
    ckpt = _BoundedLloydCheckpoint(
        path, every=every,
        bind={"carry_version": BOUNDED_CARRY_VERSION,
              "n": int(X.shape[0]), "k": int(k), "d": int(d),
              "G": int(G), "max_iter": int(max_iter)})
    snap = ckpt.load()
    if snap is None:
        state = _bounded_init_state(jnp.asarray(centers0), n_pad, G,
                                    max_iter, bdt)
    else:
        carry, _outs, _nb, _ep = snap
        state = tuple(jnp.asarray(leaf) for leaf in carry)
    tol_dev = jnp.asarray(tol, jnp.float32)
    while True:
        it, shift = int(state[4]), float(state[5])
        if it >= max_iter or not (shift >= float(jax.device_get(tol_dev))):
            break
        state = _bounded_chunk(
            X, w, state, tol_dev, max_iter=max_iter,
            chunk=int(chunk_iters), kernel=kernel, groups=groups,
            prune=prune, bounds_dtype=bounds_dtype)
        state = tuple(jax.block_until_ready(s) for s in state)
        ckpt.tick(state, [], int(state[4]), 0)
    centers = state[0]
    labels_f, inertia = _bounded_final_assign(X, w, centers, kernel=kernel)
    ckpt.delete()
    return (centers, inertia, state[4], state[5], labels_f,
            {"rows_skipped": state[6], "bounds_held": state[7]})


@jax.jit
def compute_inertia(X, w, centers):
    """Weighted cost of assigning X to ``centers``
    (reference: cluster/k_means.py:243-251)."""
    _, _, inertia = _assign(X, w, centers)
    return inertia


@jax.jit
def predict_labels(X, centers):
    return fused_argmin_min(X, centers)[0]


def sketched_assign_wins(n: int, k: int, d: int, p: int) -> bool:
    """Should assignment against a fast-transform sketch run the SKETCHED
    contraction (transform + O(n·k·p) support matmul —
    ops/fused_distance.py ``fused_argmin_min_sketched``) or the EXACT
    dense contraction against the reconstructed centers (O(n·k·d))? Both
    paths assign to the same sketched model — mathematically identical
    labels (orthogonal transform: restricted and reconstructed distances
    agree), so this is a pure perf dispatch, the
    ``_bounded_auto_wins``/``_fused_auto_wins`` pattern: bench-measured
    verdicts in the decision cache (``DECISIONS_WRITE=1 bench.py
    --sketch`` records them, rule ``kmeans.sketched.assign``) override
    the hand-written cold-start inequality point-wise. The fallback asks
    for the arithmetic win to be structural — the sketched path pays an
    O(n·d·p) staging matmul per batch, so the support must be genuinely
    narrow and k large enough that the k·p term, not the staging
    overhead, is the bill."""
    from dask_ml_tpu.parallel import decisions

    return decisions.lookup(
        "kmeans.sketched.assign",
        {"n": n, "k": k, "d": d, "p": p},
        fallback=(2 * p <= d and k >= 8))


@jax.jit
def _predict_sketched_fast(X, Wp, off, vals):
    # Zp = (X - mu) @ Wp folded into one affine map: X @ Wp - (mu @ Wp).
    # No (n, d) centered temporary, no per-call factor-ladder replay (Wp
    # is materialized ONCE at fit time — support_matrix docstring), and
    # no |x - mu|^2 pass: the argmin is invariant to the per-row x2
    # constant the epilogue would add back, and labels are all this
    # program returns, so x2=0 skips a full read-square-reduce sweep
    # over X — measured, this halves staging cost at the bench shape.
    Zp = X @ Wp.astype(X.dtype) - off[None, :].astype(X.dtype)
    zero = jnp.zeros((X.shape[0],), jnp.float32)
    return fused_argmin_min_sketched(Zp, vals, x2=zero)[0]


def predict_labels_sketched(X, Wp, off, vals, centers):
    """Labels for X under a sketched k-means model — THE one assignment
    program for the sketched family, shared by ``KMeans.fit`` (post-loop
    labels), ``KMeans.predict``, and the serving runner
    (parallel/serving.py), so served predictions are bit-identical to
    direct calls by construction. Dispatches sketched-vs-exact through
    :func:`sketched_assign_wins` at facade level (shapes are static), so
    the jitted program itself stays branch-free and compiles once per
    shape bucket). ``Wp`` is the fit-time-materialized (d, p) support
    slice of the learned transform and ``off = mu @ Wp`` its centering
    offset — the weighted data mean the fit centered on before
    sketching folds into the staging matmul as an affine shift (k-means
    geometry is translation-invariant; centering keeps the shared-mean
    direction from eating support budget). The dense ``centers`` are
    the reconstruction with the mean added back, so both dispatch
    branches assign to the SAME model and return identical labels."""
    n, d = X.shape
    k = vals.shape[0]
    p = Wp.shape[1]
    if sketched_assign_wins(n, k, d, p):
        return _predict_sketched_fast(X, Wp, off, vals)
    return predict_labels(X, centers)


@jax.jit
def scaled_tolerance(X, w, tol):
    """Scale ``tol`` by the mean per-feature variance, as sklearn and the
    reference do (reference: cluster/k_means.py:446-454)."""
    mean = (w[:, None] * X).sum(0) / w.sum()
    var = (w[:, None] * (X - mean) ** 2).sum(0) / w.sum()
    return tol * var.mean()


# ---------------------------------------------------------------------------
# Batched candidate cells (search fast path)
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("max_k", "max_iter"))
def _batched_cells_impl(X, w, uk_arr, member_uk, tol_arr, d_true, idx0,
                        eval_Xs, eval_ws, *, max_k, max_iter):
    """All (n_clusters, tol) KMeans candidates over ONE dataset as ONE XLA
    program: trajectories per unique k, per-tol stopping selection, bulk
    scoring — the driver's batched-candidate fast path (SURVEY §2.9
    task-parallelism row: "vmap over candidates when shapes are
    homogeneous"; VERDICT r3 #1).

    Three facts make this beat one-program-per-candidate by far more than
    dispatch overhead:

    - **Shared trajectories.** Candidates differing only in ``tol`` follow
      the IDENTICAL Lloyd trajectory and differ only in where they stop, so
      the program runs one ``lax.scan`` per UNIQUE ``n_clusters`` (recording
      per-iteration centers/shift) and each member just SELECTS its stopping
      iteration — 10 tol values cost one trajectory, not 10.
    - **Masked k.** Centers live in a fixed ``(max_k, d)`` buffer with an
      ``arange < k`` validity mask (invalid rows: +inf distance, frozen
      position), so every ``n_clusters`` value shares one compiled program —
      the recompilation-storm answer SURVEY §7.3 calls for ("jit with
      hyperparams as traced scalars").
    - **Bulk scoring.** Every member × eval-set inertia is computed
      on-device in one pass and fetched together: on a high-RTT host link a
      search's per-cell score fetches dominate wall time otherwise.

    Member m's config: ``k = uk_arr[member_uk[m]]``, ``tol_arr[m]`` (raw;
    scaled by mean feature variance in-program). Returns
    ``(n_iters (M,), train_inertia (M,), eval_inertias tuple of (M,))``.
    """
    n_pad, d = X.shape
    U = uk_arr.shape[0]
    kiota = jnp.arange(max_k, dtype=jnp.int32)

    # shared random init: ``idx0`` is the first max_k entries of the
    # single-fit path's _random_rows permutation, drawn EAGERLY by the host
    # entry so the true sample count never enters this program's static
    # signature — under shape bucketing a K-fold search's folds share one
    # padded X shape, and a static n_valid would have recompiled this (the
    # sweep's most expensive program) once per fold anyway. Member k uses
    # the first k sampled rows, so its trajectory matches a standalone
    # fit(random_state=...) up to a row permutation of the center buffer —
    # which leaves assignments, shifts, n_iter, and inertia unchanged.
    centers0 = jnp.take(X, idx0, axis=0).astype(jnp.float32)  # (max_k, d)

    x2 = jnp.sum(X.astype(jnp.float32) ** 2, axis=1)  # (n_pad,) invariant

    # tol scaling by mean feature variance ON DEVICE (the single-fit path's
    # scaled_tolerance, without its host fetch). The mean divides by the
    # TRUE feature count (traced) — the caller may have zero-padded the
    # feature axis for compile sharing, and padded columns (variance 0)
    # must not dilute it.
    sw = jnp.maximum(jnp.sum(w), 1.0)
    mean = (w[:, None] * X).sum(0) / sw
    var = (w[:, None] * (X - mean) ** 2).sum(0) / sw
    tol_arr = tol_arr * (var.sum() / d_true)

    # freeze threshold per unique k: once a trajectory's shift drops under
    # the SMALLEST tol of any member with that k, every member's stopping
    # index is already determined — later iterations skip the data passes
    # (lax.cond) instead of recomputing identical centers
    min_tol_uk = jnp.full((U,), jnp.inf, jnp.float32)
    min_tol_uk = min_tol_uk.at[member_uk].min(tol_arr)

    def one_k(k, min_tol):
        valid = (kiota < k)  # (max_k,)

        def lloyd(centers):
            c2 = jnp.sum(centers * centers, axis=1)
            prod = jax.lax.dot_general(
                X, centers.astype(X.dtype), (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)  # (n_pad, max_k)
            scores = jnp.where(valid[None, :], c2[None, :] - 2.0 * prod,
                               jnp.inf)
            best = jnp.argmin(scores, axis=1)
            onehot = (kiota[None, :] == best[:, None]).astype(jnp.float32)
            oh_w = onehot * w[:, None]
            sums = jax.lax.dot_general(
                oh_w, X.astype(jnp.float32), (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)  # (max_k, d)
            counts = oh_w.sum(axis=0)
            new_centers = _new_centers(sums, counts, centers, live=valid)
            shift = jnp.sum(
                jnp.where(valid[:, None], (new_centers - centers) ** 2, 0.0))
            mind = jnp.maximum(jnp.min(scores, axis=1) + x2, 0.0)
            inertia = jnp.sum(mind * w)
            return new_centers, shift, inertia

        def step(carry, _):
            centers, frozen, shift_p, inertia_p = carry
            new_centers, shift, inertia = jax.lax.cond(
                frozen,
                lambda c: (c, shift_p, inertia_p),  # no data pass
                lloyd,
                centers,
            )
            frozen = jnp.logical_or(frozen, shift < min_tol)
            return ((new_centers, frozen, shift, inertia),
                    (new_centers, shift, inertia))

        carry0 = (centers0, jnp.asarray(False),
                  jnp.asarray(jnp.inf, jnp.float32),
                  jnp.asarray(jnp.inf, jnp.float32))
        _, (hist, shifts, inertias) = jax.lax.scan(
            step, carry0, None, length=max_iter)
        return hist, shifts, inertias  # (T,max_k,d), (T,), (T,)

    # lax.map, NOT vmap: under vmap the freeze `lax.cond` would lower to a
    # select that executes BOTH branches for every lane — the data passes
    # would never be skipped. map keeps the predicate scalar per trajectory
    # so converged trajectories genuinely stop paying for Lloyd steps; each
    # trajectory's matmuls saturate the chip on their own, so sequential
    # unique-k processing costs no real parallelism.
    hist, shifts, inertias = jax.lax.map(
        lambda args: one_k(*args), (uk_arr, min_tol_uk))  # (U,T,...)

    # per-member stopping: first t with shift < tol, else T-1 (same rule as
    # lloyd_loop's `shift >= tol` while-condition, reference
    # cluster/k_means.py:496-499)
    m_shifts = shifts[member_uk]  # (M, T)
    below = m_shifts < tol_arr[:, None]
    any_below = jnp.any(below, axis=1)
    first = jnp.argmax(below, axis=1)
    stop = jnp.where(any_below, first, max_iter - 1)  # (M,)
    n_iters = stop + 1

    centers_m = hist[member_uk, stop]  # (M, max_k, d) f32
    k_m = uk_arr[member_uk]  # (M,)
    valid_m = kiota[None, :] < k_m[:, None]  # (M, max_k)
    train_inertia = inertias[member_uk, stop]  # (M,)

    def eval_inertia(Xe, we):
        xe2 = jnp.sum(Xe.astype(jnp.float32) ** 2, axis=1)  # (nE,)
        c2 = jnp.sum(centers_m * centers_m, axis=2)  # (M, max_k)
        flat = centers_m.reshape(-1, d)  # (M*max_k, d)
        prod = jax.lax.dot_general(
            Xe, flat.astype(Xe.dtype), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # (nE, M*max_k)
        prod = prod.reshape(Xe.shape[0], centers_m.shape[0], max_k)
        scores = jnp.where(valid_m[None], c2[None] - 2.0 * prod, jnp.inf)
        mind = jnp.maximum(jnp.min(scores, axis=2) + xe2[:, None], 0.0)
        return jnp.sum(mind * we[:, None], axis=0)  # (M,)

    eval_out = tuple(
        eval_inertia(Xe, we) for Xe, we in zip(eval_Xs, eval_ws)
    )
    return n_iters, train_inertia, eval_out


_BATCH_D_BUCKET = 32


def _pad_features(X, d_pad: int):
    d = X.shape[1]
    if d == d_pad:
        return X
    return jnp.pad(X, ((0, 0), (0, d_pad - d)))


def batched_lloyd_cells(data, members, eval_sets, *, max_iter, key):
    """Host entry for the batched-candidate program (see
    :func:`_batched_cells_impl`).

    ``data``: staged training :class:`DeviceData`; ``members``: list of
    ``(n_clusters, tol)``; ``eval_sets``: list of staged DeviceData to score
    (negative inertia). Returns ``(n_iters, train_inertia, [scores...])``
    as DEVICE arrays — no sync: the dispatch is async, and the search
    driver bulk-fetches every group's outputs in one ``device_get`` (a
    fetch per group costs ~2 RTT on a tunneled host link and serializes).

    The feature axis is zero-padded up to a multiple of ``_BATCH_D_BUCKET``
    before entering the program (VERDICT r4 #2: a pipeline sweep whose
    upstream PCA emits 5 different widths compiled 5 copies of this — the
    single most expensive program in the sweep's cold start). Zero columns
    change NOTHING the program returns: distances, trajectories, n_iter,
    and inertias are bit-identical, and centers never leave the program.
    One compile now serves every width in the bucket.
    """
    ks = [int(k) for k, _ in members]
    uks = sorted(set(ks))
    uk_index = {k: i for i, k in enumerate(uks)}
    max_k = max(uks)
    tol_arr = jnp.asarray([float(t) for _, t in members], jnp.float32)
    uk_arr = jnp.asarray(uks, jnp.int32)
    member_uk = jnp.asarray([uk_index[k] for k in ks], jnp.int32)
    d = int(data.X.shape[1])
    d_pad = -(-d // _BATCH_D_BUCKET) * _BATCH_D_BUCKET
    # the init draw runs eagerly (same bits as _random_rows: the first
    # max_k entries of permutation(key, n)) so the program's signature
    # depends only on SHAPES — one compile serves every fold/sample count
    # that lands in the same padding bucket
    idx0 = jax.random.permutation(key, data.n)[:max_k]
    n_iters, train_inertia, evals = _batched_cells_impl(
        _pad_features(data.X, d_pad), data.weights, uk_arr, member_uk,
        tol_arr, jnp.asarray(float(d), jnp.float32), idx0,
        tuple(_pad_features(e.X, d_pad) for e in eval_sets),
        tuple(e.weights for e in eval_sets),
        max_k=max_k, max_iter=int(max_iter))
    return n_iters, train_inertia, list(evals)


# ---------------------------------------------------------------------------
# Initialization
# ---------------------------------------------------------------------------


def _kmeanspp_on_candidates(cand, cw, n_clusters: int, key, n_trials: int):
    """On-device weighted greedy k-means++ over the (small, replicated)
    candidate buffer — the device replacement for the reference's
    driver-local sklearn finishing KMeans init
    (reference: cluster/k_means.py:418-419). Greedy local trials follow
    sklearn's ``_kmeans_plusplus``: each step draws ``n_trials`` candidates
    ∝ weighted D² and keeps the one minimizing the resulting potential.
    Invalid buffer rows carry ``cw == 0`` and can never be drawn (their
    sampling logit is a floor constant only reachable when every real
    potential is zero, i.e. fewer distinct rows than clusters)."""
    key, k0 = jax.random.split(key)
    i0 = jax.random.categorical(k0, jnp.log(jnp.maximum(cw, 1e-30)))
    c0 = cand[i0]
    centers = jnp.zeros((n_clusters, cand.shape[1]), jnp.float32).at[0].set(c0)
    mind0 = jnp.where(cw > 0, jnp.sum((cand - c0[None, :]) ** 2, axis=1), 0.0)

    def body(j, carry):
        centers, mind, key = carry
        key, kj = jax.random.split(key)
        pot = mind * cw
        ids = jax.random.categorical(
            kj, jnp.log(jnp.maximum(pot, 1e-30)), shape=(n_trials,))
        cs = cand[ids]  # (L, d)
        d2 = jnp.sum((cand[None, :, :] - cs[:, None, :]) ** 2, axis=-1)
        newmind = jnp.minimum(mind[None, :], d2)  # (L, cdim)
        b = jnp.argmin(jnp.sum(newmind * cw[None, :], axis=1))
        centers = centers.at[j].set(cs[b])
        mind = jnp.where(cw > 0, newmind[b], 0.0)
        return centers, mind, key

    centers, _, _ = jax.lax.fori_loop(
        1, n_clusters, body, (centers, mind0, key))
    return centers


def _init_seed_phase(X, w, k0, *, max_rounds: int, max_cand: int):
    """k-means|| phase 1 — seeding: first center ∝ w, initial per-row
    min-distances, φ₀, and the data-dependent round count."""
    n_padded, d = X.shape
    idx0 = jax.random.categorical(k0, jnp.log(jnp.maximum(w, 1e-30)))
    first = X[idx0].astype(jnp.float32)
    cand = jnp.zeros((max_cand, d), jnp.float32).at[0].set(first)
    mind0 = jnp.where(
        w > 0,
        jnp.sum((X.astype(jnp.float32) - first[None, :]) ** 2, axis=1),
        0.0)
    phi0 = jnp.sum(mind0 * w)
    n_rounds = jnp.clip(
        jnp.round(jnp.log(jnp.maximum(phi0, 1e-30))), 1, max_rounds
    ).astype(jnp.int32)
    return cand, mind0, phi0, n_rounds


def _init_rounds_phase(X, w, l, cand, mind0, n_rounds, key, *,
                       max_rounds: int, max_cand: int, cap: int,
                       mesh=None, kernel: str = "auto", prune: bool = True):
    """k-means|| phase 2 — the sampling rounds (incremental min-distance
    maintenance + top_k index packing; see :func:`_init_scalable_device`).
    The per-round distance+mask+min against the new rows routes through
    the fused family — on TPU the (n × cap) distance block never reaches
    HBM (``kernel='auto'`` dispatch, ops/fused_distance.py).

    ``prune=True`` (default) additionally SKIPS the distance work for rows
    whose stale minimum provably cannot improve — the bounded-Lloyd
    companion optimization (arxiv 2105.02936's norm-filter specialized to
    the incremental update): a point's min distance to the candidate set
    only shrinks, and ``d(x, c) ≥ |‖x‖ − ‖c‖|`` (reverse triangle
    inequality), so when the squared gap between ``‖x‖`` and the new
    rows' norm interval ``[r_lo, r_hi]`` already exceeds ``mind`` — minus
    an absolute slack that over-covers f32 rounding of both sides — the
    round cannot touch that row and its block skips via the family's
    ``row_need`` contract. Skipped rows keep ``mind`` bit-exactly (the
    skipped output is ``+inf``, the incremental-min identity), so pruned
    and unpruned rounds produce IDENTICAL candidate trajectories. ‖x‖ is
    loop-invariant and hoisted; the per-round extra cost is O(n) against
    the O(n·cap·d) pass it can skip. Returns two extra counters
    ``(rows_skipped, rows_considered)`` summed over executed rounds for
    the init-phase observability report."""
    n_padded = X.shape[0]
    cap_iota = jnp.arange(cap)
    if prune:
        x2 = jnp.sum(X.astype(jnp.float32) ** 2, axis=1)  # (n,) invariant
        xnorm = jnp.sqrt(x2)

    def do_round(carry):
        cand, n_cand, mind, key, overflow, skipped, considered = carry
        key, kr = jax.random.split(key)
        phi = jnp.sum(mind * w)
        p = jnp.minimum(1.0, l * mind * w / jnp.maximum(phi, 1e-30))
        draws = jax.random.uniform(kr, (n_padded,))
        mask = draws < p
        total = jnp.sum(mask)
        # pack hit indices with top_k, NOT jnp.nonzero(size=...): nonzero
        # lowers to a scatter, which serializes on TPU at this n (~40 ms a
        # round); top_k is a fast custom call, and with hits as equal 1.0
        # scores it returns hit indices (overflow beyond cap truncates —
        # same semantics as the buffer cap)
        _, idx = jax.lax.top_k(mask.astype(jnp.float32), cap)
        count = jnp.minimum(jnp.minimum(total, cap), max_cand - n_cand)
        rows = X[idx].astype(jnp.float32)  # (cap, d)
        ok = cap_iota < count
        slots = jnp.where(ok, n_cand + cap_iota, max_cand)  # OOB → dropped
        cand = cand.at[slots].set(rows, mode="drop")
        # incremental min-distance update against ONLY the new rows; the
        # ok-mask keeps unfilled slots at +inf inside the fused reduction,
        # so an empty round leaves mind unchanged
        if prune:
            rn = jnp.sqrt(jnp.sum(rows * rows, axis=1))  # (cap,) f32
            r_lo = jnp.min(jnp.where(ok, rn, jnp.inf))
            r_hi = jnp.max(jnp.where(ok, rn, 0.0))
            gap = jnp.maximum(jnp.maximum(r_lo - xnorm, xnorm - r_hi), 0.0)
            # skip only when the margin clears an absolute slack that
            # over-covers f32 rounding of the computed distance AND the
            # computed gap (~80× headroom over eps·scale²) — a skipped
            # row's minimum(mind, d̂²) is then provably a no-op even in
            # rounded arithmetic. An empty round (gap = +inf against a
            # finite slack) skips every row.
            slack = 1e-5 * (x2 + r_hi * r_hi) + 1e-12
            need = jnp.logical_and(gap * gap - slack < mind, w > 0)
            w_real = w > 0
            skipped = skipped + jnp.sum(
                jnp.logical_and(w_real, jnp.logical_not(need))
                .astype(jnp.int32))
            considered = considered + jnp.sum(w_real.astype(jnp.int32))
            dmin_new = fused_rowwise_min(X, rows, mask=ok, kernel=kernel,
                                         mesh=mesh, row_need=need)
        else:
            dmin_new = fused_rowwise_min(X, rows, mask=ok, kernel=kernel,
                                         mesh=mesh)
        mind = jnp.where(w > 0, jnp.minimum(mind, dmin_new), 0.0)
        overflow = jnp.maximum(overflow, total - count)
        return (cand, n_cand + count, mind, key, overflow, skipped,
                considered)

    def round_body(r, carry):
        return jax.lax.cond(r < n_rounds, do_round, lambda c: c, carry)

    cand, n_cand, _mind, _key, overflow, skipped, considered = \
        jax.lax.fori_loop(
            0, max_rounds, round_body,
            (cand, jnp.asarray(1, jnp.int32), mind0, key,
             jnp.asarray(0, jnp.int32), jnp.asarray(0, jnp.int32),
             jnp.asarray(0, jnp.int32)))
    return cand, n_cand, overflow, skipped, considered


def _init_weights_phase(X, w, cand, n_cand, k_extra, *, n_clusters: int,
                        max_cand: int, mesh=None, kernel: str = "auto"):
    """k-means|| phase 3 — degenerate-draw top-up + candidate weighting.
    The O(n·max_cand·d) argmin + one-hot contraction routes through
    :func:`~dask_ml_tpu.ops.fused_distance.fused_argmin_weight` — one
    implementation shared with ``pairwise_distances_argmin_min`` and the
    spectral assignment path; on TPU neither the (n × max_cand) distance
    matrix nor the one-hot ever reaches HBM."""
    slot_iota = jnp.arange(max_cand)

    # Degenerate draw (tiny data): top up to n_clusters with random
    # distinct real rows, like the reference's fallback to random
    # sampling. Behind a lax.cond (scalar predicate) so the common case
    # pays nothing; inside, the k smallest per-row uniforms (masked to
    # real rows) ARE a without-replacement uniform draw — top_k instead
    # of random.choice(replace=False), whose full permutation sort costs
    # tens of ms at millions of rows.
    need = jnp.clip(n_clusters - n_cand, 0, n_clusters)

    def top_up(cand):
        u = jax.random.uniform(k_extra, (X.shape[0],))
        u = jnp.where(w > 0, u, jnp.inf)
        _, extra_idx = jax.lax.top_k(-u, n_clusters)
        fill_iota = jnp.arange(n_clusters)
        fill_slots = jnp.where(fill_iota < need, n_cand + fill_iota,
                               max_cand)
        return cand.at[fill_slots].set(X[extra_idx].astype(jnp.float32),
                                       mode="drop")

    cand = jax.lax.cond(need > 0, top_up, lambda c: c, cand)
    n_cand = n_cand + need

    # candidate weights: total row weight assigned to each nearest
    # candidate — the fused argmin+weighted-accumulation epilogue (XLA
    # path: one-hot matmul contraction on the MXU + psum over the sharded
    # sample axis; scatter-add segment_sum serializes on TPU)
    valid = slot_iota < n_cand
    _nearest, cw = fused_argmin_weight(X, w, cand, mask=valid,
                                       kernel=kernel, mesh=mesh)
    return cand, n_cand, cw


def _init_finish_phase(cand, cw, tol, k_pp, *, n_clusters: int,
                       n_trials: int, finish_iters: int):
    """k-means|| phase 4 — weighted greedy k-means++ over the candidate
    buffer plus the small finishing Lloyd loop."""
    centers = _kmeanspp_on_candidates(cand, cw, n_clusters, k_pp, n_trials)
    centers, _, _, _ = lloyd_loop(cand, cw, centers, tol,
                                  max_iter=finish_iters)
    return centers


@partial(jax.jit, static_argnames=(
    "n_clusters", "max_rounds", "max_cand", "cap", "n_trials",
    "finish_iters", "mesh", "kernel"))
def _init_scalable_device(X, w, l, tol, key, *, n_clusters: int,
                          max_rounds: int, max_cand: int, cap: int,
                          n_trials: int, finish_iters: int,
                          mesh=None, kernel: str = "auto"):
    """The ENTIRE k-means|| init as ONE XLA program — zero host round
    trips (VERDICT r4 #1: the previous host round loop paid ~1 RTT per
    round plus host fetches for φ, candidate weights, the candidate
    buffer, and a driver-local sklearn finishing fit; at KDD scale on a
    93 ms-RTT link that was ≥90% of the whole fit).

    Measured sub-phase breakdown (the four phases run as separate
    programs by :func:`measure_init_phases`, whose per-phase wall times
    bench_kdd records next to the fused number): at a KDD-shaped 2e5×41,
    k=8, ℓ=16 slice on the 8-device CPU test mesh the split is rounds
    64% / candidate-weighting one-hot matmul 25% / seeding 11% /
    finishing k-means++ <1% — the rounds' fori_loop (up to 20 data
    passes of draw + incremental min-distance maintenance) and the
    O(n·max_cand·d) weighting pass are the two roofline terms, both
    bandwidth-bound full-data passes; the finishing cluster-down runs on
    the tiny replicated candidate buffer and is noise. TPU numbers land
    in ``BENCH_*.json`` under ``init_phase_seconds``. The fused program
    also carries ``jax.named_scope`` annotations per phase, so
    externally-captured device traces (xprof) attribute time the same
    way.

    Structure (Bahmani et al. 2012, Algorithm 2; reference:
    cluster/k_means.py:357-422):

    - seed candidate ∝ w; φ₀ and the data-dependent round count
      ``clip(round(log φ₀), 1, max_rounds)`` are computed ON DEVICE and
      the round loop is a ``fori_loop`` whose surplus iterations skip via
      ``lax.cond`` (scalar predicate — the data passes genuinely don't
      run).
    - each round keeps the per-row min-distance ``mind`` INCREMENTAL:
      only distances to the ≤``cap`` rows drawn *this* round are
      computed (O(n·cap·d) per round instead of O(n·max_cand·d) against
      the whole buffer).
    - drawn row indices are packed with a stable ``top_k`` over the hit
      mask (``jnp.nonzero(size=...)`` lowers to a scatter, which
      serializes on TPU at this n) and gathered device-side into the
      fixed ``(max_cand, d)`` buffer with a small drop-mode scatter —
      nothing crosses the host boundary.
    - candidate weights sum row weights over nearest candidates through
      the fused family's argmin+weighted-accumulation epilogue
      (ops/fused_distance.py; its XLA lowering is a ONE-HOT MATMUL on the
      MXU — reference: cluster/k_means.py:407-416; a scatter-add
      ``segment_sum`` at this n is catastrophically slow on TPU —
      colliding indices serialize the scatter; the pallas lowering keeps
      the (n × max_cand) distances AND one-hot out of HBM entirely),
      then the buffer is
      clustered down to k centers by on-device weighted greedy k-means++
      (:func:`_kmeanspp_on_candidates`) + a small weighted Lloyd loop —
      replacing the reference's driver-local sklearn finishing KMeans
      with the same math on device.

    Returns ``(centers, aux)`` where aux = (n_rounds, n_cand, φ₀,
    max round overflow beyond ``cap``, rows bound-skipped over all
    executed rounds, rows considered) — all device scalars; the caller
    fetches them in one round trip for logging/no-silent-caps warnings
    and the init-round skip-ratio observability.
    """
    key, k0, k_extra, k_pp = jax.random.split(key, 4)
    with jax.named_scope("kmeans-init-seed"):
        cand, mind0, phi0, n_rounds = _init_seed_phase(
            X, w, k0, max_rounds=max_rounds, max_cand=max_cand)
    with jax.named_scope("kmeans-init-rounds"):
        cand, n_cand, overflow, r_skip, r_total = _init_rounds_phase(
            X, w, l, cand, mind0, n_rounds, key,
            max_rounds=max_rounds, max_cand=max_cand, cap=cap,
            mesh=mesh, kernel=kernel)
    with jax.named_scope("kmeans-init-weights"):
        # (includes the degenerate-draw top-up; the finishing weighted
        # greedy k-means++ and small Lloyd loop run on the replicated
        # candidate buffer — zero-weight invalid rows contribute nothing)
        cand, n_cand, cw = _init_weights_phase(
            X, w, cand, n_cand, k_extra, n_clusters=n_clusters,
            max_cand=max_cand, mesh=mesh, kernel=kernel)
    with jax.named_scope("kmeans-init-finish"):
        centers = _init_finish_phase(
            cand, cw, tol, k_pp, n_clusters=n_clusters, n_trials=n_trials,
            finish_iters=finish_iters)
    return centers, (n_rounds, n_cand, phi0, overflow, r_skip, r_total)


def _init_scalable_config(n_padded: int, n_clusters: int,
                          oversampling_factor: float,
                          max_iter: Optional[int]) -> dict:
    """Static buffer/cap sizing shared by :func:`init_scalable` and
    :func:`measure_init_phases` — one definition so the measurement
    harness always times the same-shaped program the production init
    compiles."""
    l = float(oversampling_factor * n_clusters)
    max_rounds = 20
    if max_iter is not None:
        max_rounds = int(min(max(max_iter, 1), max_rounds))
    return dict(
        l=l,
        max_rounds=max_rounds,
        cap=int(min(max(4 * int(np.ceil(l)) + 16, 64), n_padded)),
        max_cand=int(1 + np.ceil(l) * max_rounds + n_clusters),
        n_trials=2 + int(np.log(max(n_clusters, 2))),
    )


def _init_phase_traffic(n: int, d: int, itemsize: int, *, n_rounds: int,
                        cap: int, max_cand: int, n_clusters: int,
                        n_trials: int, finish_iters: int,
                        fused_rounds: bool, fused_weights: bool) -> dict:
    """LOGICAL bytes moved per init phase — dominant terms only, so the
    roofline ratio (bytes / wall-seconds = effective GB/s) is honest about
    what each phase fundamentally must stream, not what a given lowering
    happens to spill. Per phase:

    - ``seed``: one full X pass for the first-center distances plus the
      (n,) mind write.
    - ``rounds``: per executed round, one X pass for the incremental
      min-distance update, the (n,) mind read+write, and the (n,) draw;
      the UNFUSED lowering adds the (n × cap) f32 distance intermediate's
      write + re-read — the term the fused kernel deletes (physical TPU
      traffic is larger still: the minor dim lane-pads to 128).
    - ``weights``: one X pass + the (n,) weights read + nearest write;
      unfused adds write+read of the (n × max_cand) f32 distances AND the
      (n × max_cand) bool one-hot.
    - ``finish``: replicated candidate-buffer passes (k-means++ trials +
      the small Lloyd loop) — noise at any real n.
    """
    row = n * d * itemsize
    seed = row + 4 * n
    per_round = row + 3 * 4 * n
    if not fused_rounds:
        per_round += 2 * n * cap * 4
    rounds = max(int(n_rounds), 0) * per_round
    weights = row + 2 * 4 * n
    if not fused_weights:
        weights += 2 * n * max_cand * 4 + 2 * n * max_cand
    finish = (n_clusters * n_trials + 2 * finish_iters) * max_cand * d * 4
    return dict(seed=seed, rounds=rounds, weights=weights, finish=finish)


def _init_phase_collective_traffic(mesh, d: int, *, n_rounds: int, cap: int,
                                   max_cand: int) -> dict:
    """Per-MESH-AXIS logical collective bytes per init phase — the
    cross-device companion of :func:`_init_phase_traffic`'s (per-device
    HBM-streaming) accounting, for the hierarchical scale-out report
    (docs/scale-out.md). Uses the ledger's combining model
    (:func:`~dask_ml_tpu.parallel.hierarchy.collective_bytes`: (s−1)·B per
    reduction group per axis; gathers modeled with the same rule on their
    payload). Dominant terms per phase:

    - ``seed``: the φ₀ scalar reduction.
    - ``rounds``: per executed round, the φ scalar + draw-count
      reductions and the ≤``cap``-row candidate gather into the
      replicated buffer (payload cap·d·4 — candidate rows are f32).
    - ``weights``: the (max_cand,) candidate-weight psum (the one-hot
      contraction's cross-shard combine).
    - ``finish``: replicated candidate-buffer compute — zero collective
      bytes (the zero-collective path, reported as exact 0s).
    """
    from dask_ml_tpu.parallel.hierarchy import collective_bytes

    def cb(nbytes):
        return collective_bytes(mesh, int(nbytes))

    def add(a, b):
        return {k: a.get(k, 0) + b.get(k, 0) for k in set(a) | set(b)}

    zero = {k: 0 for k in cb(0)}
    r = max(int(n_rounds), 0)
    per_round = add(add(cb(4), cb(4)), cb(cap * d * 4))
    rounds = zero
    for _ in range(r):
        rounds = add(rounds, per_round)
    return dict(seed=cb(4), rounds=rounds,
                weights=cb(max_cand * 4), finish=zero)


def measure_init_phases(X, w, n_clusters: int, key,
                        oversampling_factor: float = 2.0,
                        max_iter: Optional[int] = None,
                        mesh=None, kernel: str = "auto") -> dict:
    """Roofline breakdown of the k-means|| init: run the four sub-phases
    (seeding / sampling rounds / candidate weighting / finishing
    k-means++) as SEPARATE jitted programs — the exact helper functions
    the fused :func:`_init_scalable_device` inlines — with a completion
    fetch between phases. Returns::

        {"seconds":          {phase: wall seconds},
         "bytes_moved":      {phase: logical bytes streamed},
         "effective_gbps":   {phase: bytes_moved / seconds / 1e9},
         "fused":            {"rounds": bool, "weights": bool},
         "round_skip_ratio": fraction of (row, round) distance work the
                             rounds' norm-filter bound skipped}

    ``bytes_moved`` follows :func:`_init_phase_traffic` (logical, dominant
    terms, reflecting whether the fused kernel family or the unfused XLA
    lowering actually ran), so ``effective_gbps`` next to the wall times
    shows each phase's position against the HBM roofline and the BENCH
    trajectory can track it across PRs.

    This is a measurement harness, not a production path: the fused
    program stays one XLA program (splitting it would reintroduce host
    round-trips between phases). Each phase is warmed once so compile time
    never lands in a reported number; each timed phase runs under
    :func:`~dask_ml_tpu.utils._log.profile_phase` so externally-captured
    traces see the same names. ``bench_kdd`` records the result under
    ``init_phase_seconds`` / ``init_phase_bytes_moved`` /
    ``init_phase_effective_gbps`` (VERDICT r5 "What's weak" #2: init is
    the dominant share of the warm KDD fit and had no phase attribution).
    """
    import time

    from dask_ml_tpu.ops.fused_distance import _use_pallas
    from dask_ml_tpu.parallel import telemetry

    n, d = int(X.shape[0]), int(X.shape[1])
    cfg = _init_scalable_config(n, n_clusters, oversampling_factor, max_iter)
    max_rounds, max_cand, cap = (cfg["max_rounds"], cfg["max_cand"],
                                 cfg["cap"])
    tol = scaled_tolerance(X, w, 1e-4)
    l_dev = jnp.asarray(cfg["l"], jnp.float32)
    key, k0, k_extra, k_pp = jax.random.split(key, 4)

    seed_fn = jax.jit(partial(_init_seed_phase, max_rounds=max_rounds,
                              max_cand=max_cand))
    rounds_fn = jax.jit(partial(_init_rounds_phase, max_rounds=max_rounds,
                                max_cand=max_cand, cap=cap,
                                mesh=mesh, kernel=kernel))
    weights_fn = jax.jit(partial(_init_weights_phase, n_clusters=n_clusters,
                                 max_cand=max_cand,
                                 mesh=mesh, kernel=kernel))
    finish_fn = jax.jit(partial(_init_finish_phase, n_clusters=n_clusters,
                                n_trials=cfg["n_trials"], finish_iters=100))

    def force(out):
        # completion barrier that works even where block_until_ready is
        # advisory (tunneled backends): fetch one element of one leaf
        jax.block_until_ready(out)
        np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
        return out

    phases = {}

    def timed(name, fn, *args):
        force(fn(*args))  # warm: compile + one run
        t0 = time.perf_counter()
        with telemetry.span(f"kmeans-init/{name}", logger=logger):
            out = force(fn(*args))
        phases[name] = time.perf_counter() - t0
        return out

    cand, mind0, phi0, n_rounds = timed("seed", seed_fn, X, w, k0)
    cand, n_cand, _overflow, r_skip, r_total = timed(
        "rounds", rounds_fn, X, w, l_dev, cand, mind0, n_rounds, key)
    cand, n_cand, cw = timed(
        "weights", weights_fn, X, w, cand, n_cand, k_extra)
    timed("finish", finish_fn, cand, cw, tol, k_pp)

    fused = {
        "rounds": _use_pallas(kernel, n, cap, d, X.dtype, mesh),
        "weights": _use_pallas(kernel, n, max_cand, d, X.dtype, mesh),
    }
    traffic = _init_phase_traffic(
        n, d, int(jnp.dtype(X.dtype).itemsize),
        n_rounds=int(jax.device_get(n_rounds)), cap=cap, max_cand=max_cand,
        n_clusters=n_clusters, n_trials=cfg["n_trials"], finish_iters=100,
        fused_rounds=fused["rounds"], fused_weights=fused["weights"])
    skip_ratio = (float(jax.device_get(r_skip))
                  / max(float(jax.device_get(r_total)), 1.0))
    if telemetry.enabled():
        telemetry.metrics().gauge(
            "kmeans.init.round_skip_ratio").set(skip_ratio)
    report = {
        "seconds": phases,
        "bytes_moved": traffic,
        "effective_gbps": {
            p: traffic[p] / max(phases[p], 1e-9) / 1e9 for p in phases},
        "fused": fused,
        # norm-filter pruning of the rounds' incremental min-distance
        # update (see _init_rounds_phase): fraction of (row, round) pairs
        # whose distance work the reverse-triangle bound skipped
        "round_skip_ratio": skip_ratio,
    }
    # hierarchical scale-out companion (docs/scale-out.md): per-mesh-axis
    # collective bytes + effective GB/s per phase, under stable keys next
    # to the PR-2 per-device streaming accounting above. Only reported
    # when the ACTIVE mesh is hierarchical — on a flat mesh the ledger
    # taxonomy has one axis and the keys would duplicate nothing useful.
    from dask_ml_tpu.parallel.mesh import is_hierarchical

    if mesh is not None and is_hierarchical(mesh):
        by_axis = _init_phase_collective_traffic(
            mesh, d, n_rounds=int(jax.device_get(n_rounds)), cap=cap,
            max_cand=max_cand)
        report["bytes_moved_by_axis"] = by_axis
        report["effective_gbps_by_axis"] = {
            p: {ax: b / max(phases[p], 1e-9) / 1e9
                for ax, b in by_axis[p].items()}
            for p in phases}
    return report


def init_scalable(
    X,
    w,
    n_valid: int,
    n_clusters: int,
    key,
    oversampling_factor: float = 2.0,
    max_iter: Optional[int] = None,
    mesh=None,
    kernel: str = "auto",
):
    """k-means|| (Scalable K-Means++, Bahmani et al. 2012, Algorithm 2;
    reference: cluster/k_means.py:357-422) — one fused device program
    (:func:`_init_scalable_device`) plus a single scalar fetch for logging.

    Buffer/cap sizes are static functions of (k, ℓ, max_rounds) only, so
    the program compiles once per data shape regardless of how many
    candidates the data-dependent rounds actually draw.
    """
    cfg = _init_scalable_config(X.shape[0], n_clusters,
                                oversampling_factor, max_iter)

    # finishing tolerance: sklearn's tol=1e-4 scaled by mean feature
    # variance of the weighted data (same rule as scaled_tolerance)
    tol = scaled_tolerance(X, w, 1e-4)

    centers, aux = _init_scalable_device(
        X, w, jnp.asarray(cfg["l"], jnp.float32), tol, key,
        n_clusters=int(n_clusters), max_rounds=cfg["max_rounds"],
        max_cand=cfg["max_cand"], cap=cfg["cap"],
        n_trials=cfg["n_trials"], finish_iters=100,
        mesh=mesh, kernel=kernel)
    # ONE host round trip, for observability only (centers stay on device);
    # also serves as the init-phase completion barrier for phase timing.
    n_rounds, n_cand, phi0, overflow, r_skip, r_total = jax.device_get(aux)
    logger.info(
        "k-means|| init: phi0=%.4g, %d rounds, %d candidates, "
        "round skip ratio %.3f",
        float(phi0), int(n_rounds), int(n_cand),
        float(r_skip) / max(float(r_total), 1.0))
    if int(overflow) > 0:
        logger.warning(
            "k-means|| round drew %d candidates beyond the per-round cap "
            "of %d; the overflow was dropped (raise oversampling_factor "
            "headroom if this recurs)", int(overflow), cfg["cap"])
    return centers


def _random_rows(X, w, n_valid: int, k: int, key):
    """k distinct real (unpadded) rows, gathered to host."""
    perm = np.asarray(jax.random.permutation(key, n_valid))[:k]
    return np.asarray(X[jnp.asarray(np.sort(perm))])


def init_random(X, w, n_valid: int, n_clusters: int, key):
    """Random-row init (reference: cluster/k_means.py:344-354)."""
    return jnp.asarray(_random_rows(X, w, n_valid, n_clusters, key))


def init_pp(X, n_valid: int, n_clusters: int, key):
    """In-memory k-means++ on the gathered data — like the reference, this
    materializes X on the host and is only sensible for modest n
    (reference: cluster/k_means.py:328-341 carries the same caveat)."""
    from sklearn.cluster import kmeans_plusplus

    Xh = np.asarray(X[:n_valid])
    seed = int(jax.random.randint(key, (), 0, 2**31 - 1))
    centers, _ = kmeans_plusplus(Xh, n_clusters, random_state=seed)
    return jnp.asarray(centers)


def k_init(
    X,
    w,
    n_valid: int,
    n_clusters: int,
    key,
    init: str = "k-means||",
    oversampling_factor: float = 2.0,
    max_iter: Optional[int] = None,
    mesh=None,
):
    """Init dispatch (reference: cluster/k_means.py:254-325)."""
    if isinstance(init, (np.ndarray, jnp.ndarray)) or hasattr(init, "shape"):
        centers = jnp.asarray(init)
        if centers.shape != (n_clusters, X.shape[1]):
            raise ValueError(
                f"init array must have shape ({n_clusters}, {X.shape[1]}), "
                f"got {centers.shape}"
            )
        return centers
    if init == "k-means||":
        return init_scalable(
            X, w, n_valid, n_clusters, key,
            oversampling_factor=oversampling_factor, max_iter=max_iter,
            mesh=mesh,
        )
    if init == "k-means++":
        return init_pp(X, n_valid, n_clusters, key)
    if init == "random":
        return init_random(X, w, n_valid, n_clusters, key)
    raise ValueError(
        f"init must be 'k-means||', 'k-means++', 'random', or an array; "
        f"got {init!r}"
    )
