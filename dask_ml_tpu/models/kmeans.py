"""KMeans functional core: jitted Lloyd iterations + k-means|| initialization.

TPU-native rebuild of the reference's distributed KMeans
(reference: cluster/k_means.py — Lloyd loop ``_kmeans_single_lloyd:457-510``,
scalable init ``init_scalable:357-422``). Design mapping:

- The reference executes one dask graph per Lloyd iteration: per-block
  sklearn distance kernels (k_means.py:470-472), a Cython partial-centroid-sum
  kernel composed with ``da.atop`` (k_means.py:477-488, _k_means.pyx:29-78),
  a delayed tree-sum, and a driver-side convergence check (k_means.py:493-499).
- Here one Lloyd iteration is a single fused XLA program over the sharded
  data: distances are an ``X @ centersᵀ`` matmul on the MXU with a fused
  argmin epilogue, and the M-step is a weighted one-hot matmul
  (``onehotᵀ @ X`` — the TPU-native replacement for the Cython segment-sum;
  for small k a k×d matmul beats scatter-adds on the MXU). Cross-shard
  reduction is an XLA ``psum`` over the ICI, inserted automatically when the
  sharded sample axis is contracted. The convergence check runs on-device
  inside a ``lax.while_loop``, so a full ``fit`` is ONE XLA program with no
  per-iteration host round-trip (the reference pays a driver↔cluster barrier
  every iteration).

Padding rows carry weight 0 and therefore contribute nothing to sums, counts,
or inertia.
"""

from __future__ import annotations

import logging
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from dask_ml_tpu.ops.pairwise import sq_euclidean

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# Lloyd iterations
# ---------------------------------------------------------------------------


def _assign(X, w, centers):
    """Fused assignment: labels, weighted min-distances, inertia."""
    d2 = sq_euclidean(X, centers)
    labels = jnp.argmin(d2, axis=1)
    mind = jnp.min(d2, axis=1)
    inertia = jnp.sum(mind * w)
    return labels, mind, inertia


def _m_step(X, w, labels, centers):
    """Weighted one-hot-matmul M-step (the Cython ``_centers_dense``
    replacement, reference: _k_means.pyx:29-78). Keeps the old center for
    empty clusters instead of collapsing to zero."""
    k = centers.shape[0]
    onehot = jax.nn.one_hot(labels, k, dtype=X.dtype) * w[:, None]
    sums = onehot.T @ X  # (k, d): contraction over the sharded axis → psum
    counts = jnp.sum(onehot, axis=0)
    # counts are *weighted* sums and may legitimately be in (0, 1); clamp only
    # exact zeros (empty clusters keep their old center).
    safe = jnp.where(counts > 0, counts, 1.0)
    new_centers = jnp.where(counts[:, None] > 0, sums / safe[:, None], centers)
    return new_centers, counts


@jax.jit
def lloyd_step(X, w, centers):
    """One Lloyd iteration. Returns (new_centers, labels, inertia, shift)."""
    labels, _, inertia = _assign(X, w, centers)
    new_centers, _ = _m_step(X, w, labels, centers)
    shift = jnp.sum((new_centers - centers) ** 2)
    return new_centers, labels, inertia, shift


@partial(jax.jit, static_argnames=("max_iter",))
def lloyd_loop(X, w, centers, tol, max_iter: int):
    """Full Lloyd optimization as one on-device ``lax.while_loop``.

    Returns (centers, inertia, n_iter, shift). The loop condition matches the
    reference's driver check ``shift < tol → stop``
    (reference: cluster/k_means.py:496-499) but never leaves the device.
    """

    def cond(state):
        _, _, it, shift = state
        return jnp.logical_and(it < max_iter, shift >= tol)

    def body(state):
        centers, _, it, _ = state
        new_centers, _, inertia, shift = lloyd_step(X, w, centers)
        return (new_centers, inertia.astype(jnp.float32), it + 1,
                shift.astype(jnp.float32))

    # centers carry in f32 regardless of the caller's dtype: the M-step's
    # f32-accumulated sums promote new_centers, and a bf16 init would
    # type-mismatch the while_loop carry (lloyd_loop_fused does the same)
    init = (centers.astype(jnp.float32), jnp.asarray(jnp.inf, jnp.float32),
            jnp.asarray(0, jnp.int32), jnp.asarray(jnp.inf, jnp.float32))
    return jax.lax.while_loop(cond, body, init)


_LLOYD_BLK = 2048  # lanes per pallas block; d·BLK·4B ≈ 0.4–2 MB of VMEM


def _pallas_lloyd_supported(k: int, d: int) -> bool:
    """Shapes the single-pass kernel handles with comfortable VMEM margins.
    Shapes beyond the bound are REJECTED for an explicit ``kernel='pallas'``
    request (ValueError at trace time); ``'auto'`` selects pallas only in
    its measured winning regimes — see :func:`_pallas_auto_wins`."""
    return k <= 128 and d <= 512


def _pallas_auto_wins(k: int, d: int, dtype) -> bool:
    """The regimes where the single-pass Pallas kernel MEASURED faster than
    the two-read XLA path on TPU (full sweep in the r4 notes; every cell
    below re-measured with runtimes ≫ the host-link RTT):

    ====  ====  ========  ==============
       d     k  dtype     pallas / xla
    ====  ====  ========  ==============
      50   128  f32       **6.8×**  (XLA's two-pass collapses at k=128)
      50   128  bf16      **7.8×**
     256     8  bf16      1.84×
     256    64  bf16      1.79×
     256   128  bf16      1.57×
     512     8  bf16      2.04×
     512   128  bf16      1.51×
      50    64  f32/bf16  1.1–1.2×  (parity band — XLA kept)
      50  8–96  f32       0.5–1.0×  (XLA wins; incl. the flagship shape)
     256+  any  f32       0.9–1.1×  (parity — XLA kept)
    ====  ====  ========  ==============

    Rule distilled from the table, conservative (pallas only where it won
    ≥1.5× reliably): large-k/small-d any dtype, or bf16 with d ≥ 128.
    TPU only — on other backends the kernel runs in interpret mode and the
    measurements do not transfer."""
    if not _pallas_lloyd_supported(k, d):
        return False
    if jax.default_backend() != "tpu":
        return False
    if k >= 128 and d <= 128:
        return True
    return dtype == jnp.bfloat16 and d >= 128


def _lloyd_iter_pallas(centers, XT, w2d, n_loc: int):
    """ONE Lloyd iteration as a single pass over the shard's data.

    The XLA path reads X twice per iteration (distance matmul, then M-step
    matmul). This Pallas kernel streams feature-major blocks of X through
    VMEM once and does everything per block — distances on the MXU, argmin/
    one-hot on the VPU, and BOTH the (k, d) weighted-sum accumulation and
    the inertia reduction before the block leaves VMEM (VMEM-scratch
    accumulators, written to the outputs on the final sequential grid
    step). Halves the LOGICAL HBM traffic of the dominant loop.

    **Measured verdict (r4 regime sweep)**: on the flagship bench shape
    (1M×50, k=8, f32) the XLA two-read path runs each iteration at the
    full memory bandwidth of BOTH passes (~5.4B samples/s/chip — the
    hardware roofline for its traffic) and beats this kernel ~2×: halving
    logical traffic does not pay when Mosaic's pipeline can't saturate the
    HBM. But the full (d, k, dtype) sweep found regimes where the fusion
    WINS decisively — k=128 with small d (XLA's two-pass path collapses to
    ~235M samples/s there; this kernel sustains 1.6–1.9B, a 6.8–7.8×
    win) and bf16 inputs with d ≥ 128 (1.5–2×). ``kernel="auto"``
    dispatches on the measured rule (:func:`_pallas_auto_wins`).

    ``n_loc`` masks the final partial block (grid is ceil-div); padding
    rows inside ``n_loc`` are handled by their zero weights, as everywhere.
    """
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    k, d = centers.shape
    blk = _LLOYD_BLK
    n_pad = XT.shape[1]
    grid = (n_pad + blk - 1) // blk

    def kernel(c_ref, xt_ref, w_ref, sums_ref, counts_ref, inertia_ref,
               acc_s, acc_c, acc_i):
        j = pl.program_id(0)

        @pl.when(j == 0)
        def _():
            acc_s[:] = jnp.zeros_like(acc_s)
            acc_c[:] = jnp.zeros_like(acc_c)
            acc_i[:] = jnp.zeros_like(acc_i)

        C = c_ref[:]  # (k, d) f32
        col = j * blk + jax.lax.broadcasted_iota(jnp.int32, (1, blk), 1)
        valid = col < n_loc
        # Zero the final block's out-of-range columns with a SELECT: OOB
        # block contents are undefined (NaN in interpret mode), and
        # 0·NaN = NaN would survive a multiplicative mask and poison the
        # matmul contraction.
        Xb = jnp.where(valid, xt_ref[:], 0)  # (d, blk)
        wv = jnp.where(valid, w_ref[:], 0.0)  # (1, blk); padding rows w=0

        prod = jax.lax.dot_general(
            C.astype(Xb.dtype), Xb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)  # (k, blk) on the MXU
        c2 = jnp.sum(C * C, axis=1, keepdims=True)  # (k, 1)
        scores = c2 - 2.0 * prod
        best = jnp.argmin(scores, axis=0, keepdims=True)  # (1, blk)
        kiota = jax.lax.broadcasted_iota(jnp.int32, (k, blk), 0)
        oh_w = (kiota == best).astype(jnp.float32) * wv  # (k, blk)

        # accumulate in VMEM SCRATCH (not the output refs): revisited
        # output blocks can be written back per grid step, serializing the
        # loop on tiny DMAs — scratch stays resident, outputs are written
        # once on the final step
        acc_s[:] += jax.lax.dot_general(
            oh_w, Xb.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # (k, d) on the MXU
        acc_c[:] += jnp.sum(oh_w, axis=1, keepdims=True)  # (k, 1)
        # inertia needs ‖x‖², computed from the block already in VMEM
        x2b = jnp.sum(
            Xb.astype(jnp.float32) * Xb.astype(jnp.float32),
            axis=0, keepdims=True)  # (1, blk)
        mind = jnp.maximum(jnp.min(scores, axis=0, keepdims=True) + x2b, 0.0)
        # keep the store 2-D: Mosaic rejects scalar stores to VMEM refs
        acc_i[:] += jnp.sum(mind * wv, axis=(0, 1), keepdims=True)

        @pl.when(j == grid - 1)
        def _():
            sums_ref[:] = acc_s[:]
            counts_ref[:] = acc_c[:]
            inertia_ref[:] = acc_i[:]

    return pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((k, d), lambda j: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((d, blk), lambda j: (0, j), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, blk), lambda j: (0, j), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((k, d), lambda j: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((k, 1), lambda j: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda j: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((k, d), jnp.float32),
            jax.ShapeDtypeStruct((k, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((k, d), jnp.float32),
            pltpu.VMEM((k, 1), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
        ],
        interpret=jax.default_backend() != "tpu",
    )(centers, XT, w2d)


@partial(jax.jit, static_argnames=("mesh", "max_iter", "kernel"))
def lloyd_loop_fused(X, w, centers0, tol, *, mesh, max_iter: int,
                     kernel: str = "auto"):
    """Bandwidth-optimal Lloyd over a feature-major (transposed) copy of X.

    Two layout/scheduling facts dominate this kernel's speed on TPU, both
    found by measurement (see bench.py for the methodology):

    1. **Lane padding.** TPU tiles are (sublane, 128-lane); an (n, d) array
       with small d (the reference workload has d=50) is physically padded
       d→128 in the minor dimension, so every pass over X reads up to 2.56×
       the logical bytes. Transposing once to (d, n) moves the padding to the
       sublane dimension (50→56 for f32), making physical ≈ logical traffic.
       The transpose costs one extra pass, amortized over all Lloyd
       iterations.
    2. **Let XLA tile.** Handing the whole shard to XLA as plain matmul +
       elementwise ops beats a hand-written `lax.scan` over VMEM-sized
       blocks: XLA's own pipelined tiling overlaps HBM reads with compute,
       while a scan serializes them. (A previous revision of this kernel
       scanned manually and also collapsed to pathological block sizes when
       the per-shard row count was prime; both problems are gone.)

    Per iteration each shard computes distances as one (k, n_loc) matmul on
    the MXU with a fused argmin/one-hot/M-step epilogue — the TPU-native
    replacement for the reference's per-block Cython segment-sum + dask
    tree-reduce (reference: cluster/k_means.py:470-492, _k_means.pyx:29-78).
    The per-row ‖x‖² term is loop-invariant and hoisted out of the while_loop
    (only the ``-2·x·c + ‖c‖²`` part enters the argmin; inertia adds ‖x‖²
    back). Cross-shard reduction is one psum of (k·d + k + 1) floats per
    iteration over the ICI, and the convergence check stays on device, so the
    entire optimization is a single XLA program with no per-iteration host
    round-trip (the reference pays a driver↔cluster barrier every iteration).

    Accepts bf16 or f32 X; distances, sums, counts and inertia always
    accumulate in f32 (``preferred_element_type``). On bandwidth-bound shapes
    f32 is typically *faster* end-to-end than bf16 here, because Mosaic's
    small-d bf16 matmul tiling is less efficient — measure before switching.

    ``kernel`` selects the per-iteration implementation: ``"xla"`` is the
    two-matmul whole-shard path above; ``"pallas"`` is the single-pass
    kernel (:func:`_lloyd_iter_pallas`) that halves per-iteration logical
    HBM traffic by fusing the M-step accumulation into the distance pass.
    ``"auto"`` (default) picks per the MEASURED winning-regime rule
    (:func:`_pallas_auto_wins`): pallas for k=128-class problems with
    small d (6.8–7.8× there) and for bf16 with d ≥ 128 (1.5–2×); XLA
    everywhere else, including the flagship small-k f32 shape where its
    two-pass roofline wins.
    """
    from jax.sharding import PartitionSpec as P

    from dask_ml_tpu.parallel.mesh import DATA_AXIS

    k, d = centers0.shape
    if kernel not in ("auto", "pallas", "xla"):
        raise ValueError(f"kernel must be auto|pallas|xla, got {kernel!r}")
    if kernel == "pallas" and not _pallas_lloyd_supported(k, d):
        raise ValueError(
            f"kernel='pallas' supports k<=128, d<=512; got k={k}, d={d}")
    use_pallas = kernel == "pallas" or (
        kernel == "auto" and _pallas_auto_wins(k, d, X.dtype))

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(), P()),
        out_specs=(P(), P(), P(), P()),
        # vma typing can't see through a pallas_call (and interpret mode
        # trips on kernel-internal constants), so the pallas path runs
        # unchecked; the default XLA path keeps the check.
        check_vma=not use_pallas,
    )
    def run(X_loc, w_loc, c0, tol_):
        # One-time feature-major relayout; the barrier keeps XLA from fusing
        # the transpose into each iteration's reads (which would re-pad d
        # back onto the lane dimension).
        XT = jax.lax.optimization_barrier(X_loc.T)  # (d, n_loc)
        if use_pallas:
            w2d = w_loc[None, :].astype(jnp.float32)
        else:
            x2 = jnp.sum(XT.astype(jnp.float32) ** 2, axis=0)  # invariant
            kidx = jnp.arange(k, dtype=jnp.int32)[:, None]

        def local_stats_xla(centers):
            cx = centers.astype(XT.dtype)
            c2 = jnp.sum(centers * centers, axis=1)  # (k,) f32
            prod = jax.lax.dot_general(
                cx, XT, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)  # (k, n_loc)
            scores = c2[:, None] - 2.0 * prod
            best = jnp.argmin(scores, axis=0).astype(jnp.int32)
            onehot = (kidx == best[None, :]).astype(jnp.float32)
            oh_w = onehot * w_loc[None, :]
            sums = jax.lax.dot_general(
                oh_w, XT.astype(jnp.float32), (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)  # (k, d)
            counts = oh_w.sum(axis=1)
            mind = jnp.maximum(jnp.min(scores, axis=0) + x2, 0.0)
            inertia = jnp.sum(mind * w_loc)
            return sums, counts, inertia

        def local_stats_pallas(centers):
            sums, counts2d, inert = _lloyd_iter_pallas(
                centers, XT, w2d, int(XT.shape[1]))
            return sums, counts2d[:, 0], inert[0, 0]

        local_stats = local_stats_pallas if use_pallas else local_stats_xla

        def one_iter(centers):
            sums, counts, inertia = local_stats(centers)
            sums = jax.lax.psum(sums, DATA_AXIS)
            counts = jax.lax.psum(counts, DATA_AXIS)
            inertia = jax.lax.psum(inertia, DATA_AXIS)
            safe = jnp.where(counts > 0, counts, 1.0)
            new_centers = jnp.where(
                counts[:, None] > 0, sums / safe[:, None], centers)
            shift = jnp.sum((new_centers - centers) ** 2)
            return new_centers, inertia, shift

        def cond(state):
            _, _, it, shift = state
            return jnp.logical_and(it < max_iter, shift >= tol_)

        def body(state):
            centers, _, it, _ = state
            new_centers, inertia, shift = one_iter(centers)
            return new_centers, inertia, it + 1, shift

        init = (c0.astype(jnp.float32),
                jnp.asarray(jnp.inf, jnp.float32),
                jnp.asarray(0, jnp.int32),
                jnp.asarray(jnp.inf, jnp.float32))
        return jax.lax.while_loop(cond, body, init)

    return run(X, w, centers0.astype(jnp.float32),
               jnp.asarray(tol, jnp.float32))


@jax.jit
def compute_inertia(X, w, centers):
    """Weighted cost of assigning X to ``centers``
    (reference: cluster/k_means.py:243-251)."""
    _, _, inertia = _assign(X, w, centers)
    return inertia


@jax.jit
def predict_labels(X, centers):
    d2 = sq_euclidean(X, centers)
    return jnp.argmin(d2, axis=1)


@jax.jit
def scaled_tolerance(X, w, tol):
    """Scale ``tol`` by the mean per-feature variance, as sklearn and the
    reference do (reference: cluster/k_means.py:446-454)."""
    mean = (w[:, None] * X).sum(0) / w.sum()
    var = (w[:, None] * (X - mean) ** 2).sum(0) / w.sum()
    return tol * var.mean()


# ---------------------------------------------------------------------------
# Batched candidate cells (search fast path)
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("max_k", "max_iter", "n_valid"))
def _batched_cells_impl(X, w, uk_arr, member_uk, tol_arr, key, eval_Xs,
                        eval_ws, *, max_k, max_iter, n_valid):
    """All (n_clusters, tol) KMeans candidates over ONE dataset as ONE XLA
    program: trajectories per unique k, per-tol stopping selection, bulk
    scoring — the driver's batched-candidate fast path (SURVEY §2.9
    task-parallelism row: "vmap over candidates when shapes are
    homogeneous"; VERDICT r3 #1).

    Three facts make this beat one-program-per-candidate by far more than
    dispatch overhead:

    - **Shared trajectories.** Candidates differing only in ``tol`` follow
      the IDENTICAL Lloyd trajectory and differ only in where they stop, so
      the program runs one ``lax.scan`` per UNIQUE ``n_clusters`` (recording
      per-iteration centers/shift) and each member just SELECTS its stopping
      iteration — 10 tol values cost one trajectory, not 10.
    - **Masked k.** Centers live in a fixed ``(max_k, d)`` buffer with an
      ``arange < k`` validity mask (invalid rows: +inf distance, frozen
      position), so every ``n_clusters`` value shares one compiled program —
      the recompilation-storm answer SURVEY §7.3 calls for ("jit with
      hyperparams as traced scalars").
    - **Bulk scoring.** Every member × eval-set inertia is computed
      on-device in one pass and fetched together: on a high-RTT host link a
      search's per-cell score fetches dominate wall time otherwise.

    Member m's config: ``k = uk_arr[member_uk[m]]``, ``tol_arr[m]`` (raw;
    scaled by mean feature variance in-program). Returns
    ``(n_iters (M,), train_inertia (M,), eval_inertias tuple of (M,))``.
    """
    n_pad, d = X.shape
    U = uk_arr.shape[0]
    kiota = jnp.arange(max_k, dtype=jnp.int32)

    # shared random init mirroring the single-fit path's _random_rows draw
    # (same permutation of the same key): member k uses the first k sampled
    # rows, so its trajectory matches a standalone fit(random_state=...) up
    # to a row permutation of the center buffer — which leaves assignments,
    # shifts, n_iter, and inertia unchanged
    idx0 = jax.random.permutation(key, n_valid)[:max_k]
    centers0 = jnp.take(X, idx0, axis=0).astype(jnp.float32)  # (max_k, d)

    x2 = jnp.sum(X.astype(jnp.float32) ** 2, axis=1)  # (n_pad,) invariant

    # tol scaling by mean feature variance ON DEVICE (the single-fit path's
    # scaled_tolerance, without its host fetch)
    sw = jnp.maximum(jnp.sum(w), 1.0)
    mean = (w[:, None] * X).sum(0) / sw
    var = (w[:, None] * (X - mean) ** 2).sum(0) / sw
    tol_arr = tol_arr * var.mean()

    # freeze threshold per unique k: once a trajectory's shift drops under
    # the SMALLEST tol of any member with that k, every member's stopping
    # index is already determined — later iterations skip the data passes
    # (lax.cond) instead of recomputing identical centers
    min_tol_uk = jnp.full((U,), jnp.inf, jnp.float32)
    min_tol_uk = min_tol_uk.at[member_uk].min(tol_arr)

    def one_k(k, min_tol):
        valid = (kiota < k)  # (max_k,)

        def lloyd(centers):
            c2 = jnp.sum(centers * centers, axis=1)
            prod = jax.lax.dot_general(
                X, centers.astype(X.dtype), (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)  # (n_pad, max_k)
            scores = jnp.where(valid[None, :], c2[None, :] - 2.0 * prod,
                               jnp.inf)
            best = jnp.argmin(scores, axis=1)
            onehot = (kiota[None, :] == best[:, None]).astype(jnp.float32)
            oh_w = onehot * w[:, None]
            sums = jax.lax.dot_general(
                oh_w, X.astype(jnp.float32), (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)  # (max_k, d)
            counts = oh_w.sum(axis=0)
            live = jnp.logical_and(valid, counts > 0)
            safe = jnp.where(counts > 0, counts, 1.0)
            new_centers = jnp.where(live[:, None], sums / safe[:, None],
                                    centers)
            shift = jnp.sum(
                jnp.where(valid[:, None], (new_centers - centers) ** 2, 0.0))
            mind = jnp.maximum(jnp.min(scores, axis=1) + x2, 0.0)
            inertia = jnp.sum(mind * w)
            return new_centers, shift, inertia

        def step(carry, _):
            centers, frozen, shift_p, inertia_p = carry
            new_centers, shift, inertia = jax.lax.cond(
                frozen,
                lambda c: (c, shift_p, inertia_p),  # no data pass
                lloyd,
                centers,
            )
            frozen = jnp.logical_or(frozen, shift < min_tol)
            return ((new_centers, frozen, shift, inertia),
                    (new_centers, shift, inertia))

        carry0 = (centers0, jnp.asarray(False),
                  jnp.asarray(jnp.inf, jnp.float32),
                  jnp.asarray(jnp.inf, jnp.float32))
        _, (hist, shifts, inertias) = jax.lax.scan(
            step, carry0, None, length=max_iter)
        return hist, shifts, inertias  # (T,max_k,d), (T,), (T,)

    # lax.map, NOT vmap: under vmap the freeze `lax.cond` would lower to a
    # select that executes BOTH branches for every lane — the data passes
    # would never be skipped. map keeps the predicate scalar per trajectory
    # so converged trajectories genuinely stop paying for Lloyd steps; each
    # trajectory's matmuls saturate the chip on their own, so sequential
    # unique-k processing costs no real parallelism.
    hist, shifts, inertias = jax.lax.map(
        lambda args: one_k(*args), (uk_arr, min_tol_uk))  # (U,T,...)

    # per-member stopping: first t with shift < tol, else T-1 (same rule as
    # lloyd_loop's `shift >= tol` while-condition, reference
    # cluster/k_means.py:496-499)
    m_shifts = shifts[member_uk]  # (M, T)
    below = m_shifts < tol_arr[:, None]
    any_below = jnp.any(below, axis=1)
    first = jnp.argmax(below, axis=1)
    stop = jnp.where(any_below, first, max_iter - 1)  # (M,)
    n_iters = stop + 1

    centers_m = hist[member_uk, stop]  # (M, max_k, d) f32
    k_m = uk_arr[member_uk]  # (M,)
    valid_m = kiota[None, :] < k_m[:, None]  # (M, max_k)
    train_inertia = inertias[member_uk, stop]  # (M,)

    def eval_inertia(Xe, we):
        xe2 = jnp.sum(Xe.astype(jnp.float32) ** 2, axis=1)  # (nE,)
        c2 = jnp.sum(centers_m * centers_m, axis=2)  # (M, max_k)
        flat = centers_m.reshape(-1, d)  # (M*max_k, d)
        prod = jax.lax.dot_general(
            Xe, flat.astype(Xe.dtype), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # (nE, M*max_k)
        prod = prod.reshape(Xe.shape[0], centers_m.shape[0], max_k)
        scores = jnp.where(valid_m[None], c2[None] - 2.0 * prod, jnp.inf)
        mind = jnp.maximum(jnp.min(scores, axis=2) + xe2[:, None], 0.0)
        return jnp.sum(mind * we[:, None], axis=0)  # (M,)

    eval_out = tuple(
        eval_inertia(Xe, we) for Xe, we in zip(eval_Xs, eval_ws)
    )
    return n_iters, train_inertia, eval_out


def batched_lloyd_cells(data, members, eval_sets, *, max_iter, key):
    """Host entry for the batched-candidate program (see
    :func:`_batched_cells_impl`).

    ``data``: staged training :class:`DeviceData`; ``members``: list of
    ``(n_clusters, tol)``; ``eval_sets``: list of staged DeviceData to score
    (negative inertia). Returns ``(n_iters, train_inertia, [scores...])``
    as DEVICE arrays — no sync: the dispatch is async, and the search
    driver bulk-fetches every group's outputs in one ``device_get`` (a
    fetch per group costs ~2 RTT on a tunneled host link and serializes).
    """
    ks = [int(k) for k, _ in members]
    uks = sorted(set(ks))
    uk_index = {k: i for i, k in enumerate(uks)}
    max_k = max(uks)
    tol_arr = jnp.asarray([float(t) for _, t in members], jnp.float32)
    uk_arr = jnp.asarray(uks, jnp.int32)
    member_uk = jnp.asarray([uk_index[k] for k in ks], jnp.int32)
    n_iters, train_inertia, evals = _batched_cells_impl(
        data.X, data.weights, uk_arr, member_uk, tol_arr, key,
        tuple(e.X for e in eval_sets), tuple(e.weights for e in eval_sets),
        max_k=max_k, max_iter=int(max_iter), n_valid=data.n)
    return n_iters, train_inertia, list(evals)


# ---------------------------------------------------------------------------
# Initialization
# ---------------------------------------------------------------------------


@jax.jit
def _min_sq_dist(X, w, candidates, cand_valid):
    """Per-row squared distance to the nearest *valid* candidate; padding rows
    (w == 0) report 0 so they never contribute to cost or sampling."""
    d2 = sq_euclidean(X, candidates)
    d2 = jnp.where(cand_valid[None, :], d2, jnp.inf)
    mind = jnp.min(d2, axis=1)
    return jnp.where(w > 0, mind, 0.0)


@jax.jit
def _sample_round(X, w, candidates, cand_valid, l, key):
    """One k-means|| oversampling round (reference: cluster/k_means.py:431-450):
    select each point independently with prob min(1, l·d²(x)/φ)."""
    mind = _min_sq_dist(X, w, candidates, cand_valid)
    phi = jnp.sum(mind * w)
    p = jnp.minimum(1.0, l * mind * w / jnp.maximum(phi, 1e-30))
    draws = jax.random.uniform(key, (X.shape[0],))
    return (draws < p), phi


@partial(jax.jit, static_argnames=("cap",))
def _sample_round_packed(X, w, candidates, cand_valid, l, key, *, cap):
    """:func:`_sample_round` with the selected ROW INDICES packed on device
    (``jnp.nonzero(..., size=cap)``): the host fetches a (cap,)-int vector
    + a count instead of the full n-row selection mask — on a slow host
    link the mask fetch dominated every init round at KDD scale. ``cap``
    bounds the draw (expected draws ≈ l; the buffer-truncation semantics
    of the caller already drop overflow)."""
    mask, phi = _sample_round(X, w, candidates, cand_valid, l, key)
    idx = jnp.nonzero(mask, size=cap, fill_value=0)[0]
    count = jnp.minimum(jnp.sum(mask), cap)
    return idx, count, phi


@jax.jit
def _candidate_weights(X, w, candidates, cand_valid):
    """Weight of each candidate = total weight of the points nearest to it
    (reference: cluster/k_means.py:407-416 uses assignment counts)."""
    d2 = sq_euclidean(X, candidates)
    d2 = jnp.where(cand_valid[None, :], d2, jnp.inf)
    nearest = jnp.argmin(d2, axis=1)
    onehot = jax.nn.one_hot(nearest, candidates.shape[0], dtype=X.dtype)
    return (onehot * w[:, None]).sum(axis=0)


def _finish_on_candidates(candidates, cweights, n_clusters, seed):
    """Cluster the small gathered candidate set down to k centers with a
    local weighted KMeans — same finishing move as the reference
    (reference: cluster/k_means.py:418-419 runs sklearn KMeans on candidates)."""
    from sklearn.cluster import KMeans as SKKMeans

    km = SKKMeans(n_clusters=n_clusters, n_init=1, random_state=seed)
    km.fit(candidates, sample_weight=np.maximum(cweights, 1e-12))
    return km.cluster_centers_.astype(candidates.dtype)


def init_scalable(
    X,
    w,
    n_valid: int,
    n_clusters: int,
    key,
    oversampling_factor: float = 2.0,
    max_iter: Optional[int] = None,
):
    """k-means|| (Scalable K-Means++, Bahmani et al. 2012, Algorithm 2;
    reference: cluster/k_means.py:357-422).

    The outer round loop stays on the host (round count is data-dependent,
    ``round(log φ)``), but each round is a fixed-shape jitted pass over the
    sharded data against a padded candidate buffer, so the whole init compiles
    exactly once regardless of how many candidates are drawn.
    """
    n_padded, d = X.shape
    l = float(oversampling_factor * n_clusters)

    # Seed candidate: one row sampled ∝ w (uniform over real rows).
    key, k0 = jax.random.split(key)
    idx0 = int(jax.random.categorical(k0, jnp.log(jnp.maximum(w, 1e-30))))
    first = np.asarray(X[idx0])

    # Initial cost vs the single seed determines the round count.
    buf1 = jnp.zeros((1, d), X.dtype).at[0].set(first)
    phi = float(jnp.sum(_min_sq_dist(X, w, buf1, jnp.ones(1, bool)) * w))
    n_rounds = int(min(max(np.round(np.log(max(phi, 1e-30))), 1), 20))
    if max_iter is not None:
        n_rounds = int(min(max(max_iter, 1), n_rounds))
    logger.info("k-means|| init: phi=%.4g, %d rounds", phi, n_rounds)

    # Fixed-size candidate buffer, kept ON DEVICE: each round gathers the
    # newly drawn rows with a device-side take + dynamic_update_slice instead
    # of re-uploading the whole buffer from host (only the row-index vector
    # crosses the host boundary, because its size is data-dependent).
    max_cand = int(1 + np.ceil(l) * n_rounds)
    cand_dev = jnp.zeros((max_cand, d), X.dtype).at[0].set(jnp.asarray(first))
    n_cand = 1

    valid = jnp.arange(max_cand) < n_cand
    # device-packed index fetch per round: (cap,) ints instead of the full
    # n-row selection mask; cap ≫ the expected l draws, and the candidate
    # buffer truncates overflow exactly as before
    cap = int(min(max(4 * int(np.ceil(l)) + 16, 64), n_padded))
    for r in range(n_rounds):
        key, kr = jax.random.split(key)
        idx_dev, cnt_dev, _phi = _sample_round_packed(
            X, w, cand_dev, valid, l, kr, cap=cap)
        idx_h, cnt = jax.device_get((idx_dev, cnt_dev))  # ONE round trip
        idx = np.asarray(idx_h)[: int(cnt)]
        if idx.size == 0:
            continue
        take = min(idx.size, max_cand - n_cand)
        if take < idx.size:
            idx = idx[:take]
        if take == 0:
            break
        rows = jnp.take(X, jnp.asarray(idx), axis=0)
        cand_dev = jax.lax.dynamic_update_slice(cand_dev, rows, (n_cand, 0))
        n_cand += take
        valid = jnp.arange(max_cand) < n_cand

    if n_cand < n_clusters:
        # Degenerate draw (tiny data): top up with random distinct rows,
        # like the reference falls back to random sampling.
        key, kf = jax.random.split(key)
        extra = jnp.asarray(_random_rows(X, w, n_valid,
                                         n_clusters - n_cand, kf))
        cand_dev = jax.lax.dynamic_update_slice(cand_dev, extra, (n_cand, 0))
        n_cand += int(extra.shape[0])
        valid = jnp.arange(max_cand) < n_cand

    cweights = np.asarray(_candidate_weights(X, w, cand_dev, valid))[:n_cand]
    cand = np.asarray(cand_dev[:n_cand], dtype=np.float32)
    seed = int(jax.random.randint(key, (), 0, 2**31 - 1))
    centers = _finish_on_candidates(cand, cweights, n_clusters, seed)
    return jnp.asarray(centers)


def _random_rows(X, w, n_valid: int, k: int, key):
    """k distinct real (unpadded) rows, gathered to host."""
    perm = np.asarray(jax.random.permutation(key, n_valid))[:k]
    return np.asarray(X[jnp.asarray(np.sort(perm))])


def init_random(X, w, n_valid: int, n_clusters: int, key):
    """Random-row init (reference: cluster/k_means.py:344-354)."""
    return jnp.asarray(_random_rows(X, w, n_valid, n_clusters, key))


def init_pp(X, n_valid: int, n_clusters: int, key):
    """In-memory k-means++ on the gathered data — like the reference, this
    materializes X on the host and is only sensible for modest n
    (reference: cluster/k_means.py:328-341 carries the same caveat)."""
    from sklearn.cluster import kmeans_plusplus

    Xh = np.asarray(X[:n_valid])
    seed = int(jax.random.randint(key, (), 0, 2**31 - 1))
    centers, _ = kmeans_plusplus(Xh, n_clusters, random_state=seed)
    return jnp.asarray(centers)


def k_init(
    X,
    w,
    n_valid: int,
    n_clusters: int,
    key,
    init: str = "k-means||",
    oversampling_factor: float = 2.0,
    max_iter: Optional[int] = None,
):
    """Init dispatch (reference: cluster/k_means.py:254-325)."""
    if isinstance(init, (np.ndarray, jnp.ndarray)) or hasattr(init, "shape"):
        centers = jnp.asarray(init)
        if centers.shape != (n_clusters, X.shape[1]):
            raise ValueError(
                f"init array must have shape ({n_clusters}, {X.shape[1]}), "
                f"got {centers.shape}"
            )
        return centers
    if init == "k-means||":
        return init_scalable(
            X, w, n_valid, n_clusters, key,
            oversampling_factor=oversampling_factor, max_iter=max_iter,
        )
    if init == "k-means++":
        return init_pp(X, n_valid, n_clusters, key)
    if init == "random":
        return init_random(X, w, n_valid, n_clusters, key)
    raise ValueError(
        f"init must be 'k-means||', 'k-means++', 'random', or an array; "
        f"got {init!r}"
    )
