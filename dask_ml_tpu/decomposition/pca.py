"""PCA on row-sharded tall-skinny arrays.

TPU-native rebuild of the reference PCA (reference: decomposition/pca.py).
The reference leans on dask's ``da.linalg.svd`` (tsqr) / ``svd_compressed``
(pca.py:233-241); here the factorizations are this build's own shard_map
programs (:mod:`dask_ml_tpu.ops.linalg`). Solver policy, explained-variance /
Probabilistic-PCA noise-variance bookkeeping, svd_flip determinism, whitening
and the PPCA score path all mirror the reference's semantics
(pca.py:182-292, 303-434).

One jitted program computes mean-centering, the factorization and the
variance bookkeeping; only the final small results land on host (the
reference similarly batches all 9 outputs into a single ``compute()``,
pca.py:278-292).
"""

from __future__ import annotations

import contextlib
import logging
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from sklearn.base import BaseEstimator, TransformerMixin

from dask_ml_tpu.config import maybe_host
from dask_ml_tpu.ops import linalg
from dask_ml_tpu.parallel import mesh as mesh_lib
from dask_ml_tpu.parallel.sharding import prepare_data, shard_rows, unpad_rows
from dask_ml_tpu.parallel import telemetry
from dask_ml_tpu.utils.validation import check_array, check_random_state

logger = logging.getLogger(__name__)


@jax.jit
def _weighted_mean(X, w):
    return (w[:, None] * X).sum(0) / jnp.maximum(w.sum(), 1.0)


@jax.jit
def _project(Xs, mean, components):
    return (Xs - mean) @ components.T


@partial(jax.jit, static_argnames=("whiten",))
def transform_program(Xs, mean, components, explained_variance, *,
                      whiten: bool):
    """The WHOLE transform (center, project, optional whitening) as one
    jitted program over staged rows — one executable per shape bucket,
    shared by the direct :meth:`PCA.transform` path and the serving loop's
    batch runners (:mod:`dask_ml_tpu.parallel.serving`), so served
    results are structurally bit-identical to direct calls."""
    out = _project(Xs, mean, components)
    if whiten:
        out = out / jnp.sqrt(explained_variance.astype(out.dtype))
    return out


@jax.jit
def _center_and_mask(X, w, mean):
    # Padding rows must stay exact zeros after centering so they vanish from
    # R in the tsqr (see ops/linalg.py module docstring).
    return (X - mean) * (w > 0)[:, None].astype(X.dtype)


@jax.jit
def _total_var(Xc, n):
    # ddof=1 column variance sum of the centered data (padding rows are 0
    # and contribute nothing); reference: pca.py:249 ``X.var(ddof=1)``.
    return (Xc * Xc).sum() / (n - 1.0)


@partial(jax.jit, static_argnames=("k", "n_power_iter", "randomized",
                                   "mesh", "sketch_dtype"))
def _fit_program(X, w, key, n, *, k, n_power_iter, randomized, mesh,
                 sketch_dtype=None):
    """The whole PCA device fit as ONE program: mean, centering+masking,
    the factorization, sign flip, and total variance. One dispatch instead
    of five — on a high-latency host link, per-op dispatch cost dominates
    small fits (a CV sweep runs many).

    ``sketch_dtype`` (static; resolved by the caller from the precision
    policy, docs/precision.md) sets the randomized range finder's matmul
    operand dtype: the sketch ``Y = X·Ω`` and power-iteration passes run
    low precision with f32 accumulation while the CholeskyQR2 repair and
    small SVD stay f32. ``None`` follows the data dtype; the exact tsqr
    path upcasts low-precision input itself (ops/linalg.py)."""
    from dask_ml_tpu.ops import linalg
    from dask_ml_tpu.parallel import hierarchy as hier

    # Feature-sharded fits (under an active model_metered scope, i.e. the
    # facade staged X P(..., 'model')): record the model-axis collectives
    # GSPMD/the tsqr in_specs insert, analytically, at TRACE time — the
    # column gather that reassembles each row shard's full width for the
    # factorization, and the (k, d) components gather on the way out.
    hier.record_model_collective("pca.colgather", X.shape, X.dtype)
    hier.record_model_collective("pca.components.gather",
                                 (k, int(X.shape[1])), jnp.float32)
    mean = _weighted_mean(X, w)
    Xc = _center_and_mask(X, w, mean)
    if randomized:
        U, S, Vt = linalg._svd_compressed_impl(
            Xc, key, k=k, n_power_iter=n_power_iter, n_oversamples=10,
            compute_dtype=sketch_dtype)
    else:
        U, S, Vt = linalg._tsvd_impl(Xc, mesh=mesh)
    U, Vt = linalg.svd_flip(U, Vt)
    # only the randomized path needs the full-data variance (the exact
    # path's total variance IS sum(S²)/(n-1)); gating avoids a wasted
    # O(n·d) reduction per exact fit
    total_var = (_total_var(Xc, n) if randomized
                 else jnp.asarray(0.0, jnp.float32))
    return mean, U, S, Vt, total_var


class PCA(BaseEstimator, TransformerMixin):
    """Principal component analysis (reference: decomposition/pca.py:12-167
    docstring; identical hyperparameter surface).

    ``svd_solver``: 'auto' | 'full' | 'tsqr' | 'randomized' — 'full' and
    'tsqr' both run the distributed tsqr SVD (as in the reference, where both
    hit ``da.linalg.svd``, pca.py:231-233); 'randomized' runs the compressed
    range-finder path with ``iterated_power`` QR power iterations.
    """

    def __init__(self, n_components=None, copy=True, whiten=False,
                 svd_solver="auto", tol=0.0, iterated_power=0,
                 random_state=None):
        self.n_components = n_components
        self.copy = copy
        self.whiten = whiten
        self.svd_solver = svd_solver
        self.tol = tol
        self.iterated_power = iterated_power
        self.random_state = random_state

    # -- fitting -----------------------------------------------------------

    def _resolve_solver(self, n_samples, n_features, n_components):
        """Solver policy (reference: pca.py:202-210)."""
        solver = self.svd_solver
        if solver == "auto":
            if max(n_samples, n_features) <= 500:
                solver = "full"
            elif 1 <= n_components < 0.8 * min(n_samples, n_features):
                solver = "randomized"
            else:
                solver = "full"
        return solver

    def _fit(self, X):
        solvers = {"full", "auto", "tsqr", "randomized"}
        if self.svd_solver not in solvers:
            raise ValueError(
                f"Invalid solver '{self.svd_solver}'. Must be one of {solvers}"
            )
        X = check_array(X)
        n_samples, n_features = int(X.shape[0]), int(X.shape[1])
        if self.n_components is None:
            n_components = min(X.shape)
        elif 0 < self.n_components < 1:
            raise NotImplementedError(
                "Fractional 'n_components' is not currently supported "
                "(same restriction as the reference, pca.py:194-196)"
            )
        else:
            n_components = int(self.n_components)

        solver = self._resolve_solver(n_samples, n_features, n_components)
        lower_limit = 1 if solver == "randomized" else 0
        if not (min(n_samples, n_features) >= n_components >= lower_limit):
            raise ValueError(
                f"n_components={n_components} must be between {lower_limit} "
                f"and min(n_samples, n_features)={min(n_samples, n_features)} "
                f"with svd_solver='{solver}'"
            )

        mesh = mesh_lib.default_mesh()
        # Feature-axis tensor parallelism (SURVEY §2.9): on a 2-D
        # ('data', 'model') mesh stage X over BOTH axes when n_features
        # divides the model axis — GSPMD then splits every d-axis
        # contraction (the Gram work of the power iterations, the Qᵀ·X
        # projections) across devices. The even-division restriction keeps
        # the variance bookkeeping exact (zero padding columns would enter
        # n_features-dependent formulas); GLMs, whose coefficients slice
        # cleanly, take the padded path instead.
        shard_features = (
            mesh_lib.n_model_shards(mesh) > 1
            and n_features % mesh_lib.n_model_shards(mesh) == 0
        )
        data = prepare_data(X, mesh=mesh, shard_features=shard_features)
        randomized = solver == "randomized"
        # Bucket the randomized sketch rank to a 32-multiple: a CV sweep
        # over n_components then shares ONE compiled fit program instead
        # of one per value (VERDICT r4 #2 — five ~4.5 s `_fit_program`
        # compiles dominated the sweep's cold start). The surplus
        # components are sliced off below; the larger sketch only
        # IMPROVES the rank-k approximation.
        k_fit = n_components
        if randomized:
            k_fit = min(-(-n_components // 32) * 32,
                        min(n_samples, n_features))
        key = check_random_state(self.random_state)
        # the precision policy's sketch dtype, resolved OUTSIDE the jit so
        # it keys the compile cache as a static argument (docs/precision.md)
        from dask_ml_tpu.parallel import precision as precision_lib

        sketch_dtype = (precision_lib.resolve().compute_for("sketch")
                        if randomized else None)
        from dask_ml_tpu.parallel import hierarchy as hier

        with telemetry.span("pca-fit-program", logger=logger,
                    solver=solver, k=int(n_components)), \
                (hier.model_metered(mesh) if shard_features
                 else contextlib.nullcontext()):
            # centering + masking + factorization + sign flip + total
            # variance as one dispatch (see _fit_program). The metered
            # scope makes the feature-sharded fit's model-axis collectives
            # record INSIDE the traced program — per trace, so repeat fits
            # (cache hits) add nothing and the compile-once <=> ledger
            # gate holds.
            mean, U, S, Vt, tv = _fit_program(
                data.X, data.weights, key, float(n_samples),
                k=k_fit, n_power_iter=int(self.iterated_power),
                randomized=randomized, mesh=mesh,
                sketch_dtype=sketch_dtype)

        # tsvd on the padded array can return min(n_padded, d) singular
        # values; only min(n_samples, d) are real (padding rows are zeros, so
        # the surplus values are exact zeros) — trim before bookkeeping or
        # the noise-variance tail mean gets diluted.
        from dask_ml_tpu.config import get_config

        # Under device_outputs (the search driver's all-jax-native scope)
        # learned attrs stay device arrays and fit() never syncs — the whole
        # fit is one async dispatch chain. np.asarray on any attr still
        # materializes it for interactive use.
        lazy = get_config()["device_outputs"]
        to_host = (lambda a: a) if lazy else np.asarray
        S_t = to_host(S[: min(n_samples, n_features)])
        explained_variance = (S_t ** 2) / (n_samples - 1)
        if solver == "randomized":
            total_var = tv if lazy else float(tv)
        else:
            total_var = explained_variance.sum()
        explained_variance_ratio = explained_variance / total_var

        # Probabilistic-PCA noise variance (reference: pca.py:262-276).
        if n_components < min(n_features, n_samples):
            if solver == "randomized":
                # sum only the REQUESTED components: the bucketed sketch
                # (k_fit >= n_components) returns surplus values that
                # belong to the noise tail, not the explained mass
                noise_variance = (
                    (total_var - explained_variance[:n_components].sum())
                    / (min(n_features, n_samples) - n_components)
                )
            else:
                noise_variance = explained_variance[n_components:].mean()
        else:
            noise_variance = 0.0

        self.n_samples_ = n_samples
        self.n_features_ = n_features
        self.n_components_ = n_components
        self.mean_ = to_host(mean)
        self.components_ = to_host(Vt[:n_components])
        self.explained_variance_ = explained_variance[:n_components]
        self.explained_variance_ratio_ = explained_variance_ratio[:n_components]
        self.singular_values_ = S_t[:n_components]
        self.noise_variance_ = (noise_variance if lazy
                                else float(noise_variance))
        return U, S, Vt, data.n

    def fit(self, X, y=None):
        self._fit(X)
        return self

    def fit_transform(self, X, y=None):
        """Returns U·S (or U·sqrt(n−1) when whitening) without a second data
        pass (reference: pca.py:330-357)."""
        U, S, Vt, n = self._fit(X)
        k = self.n_components_
        Uk = unpad_rows(U, n)[:, :k]
        if self.whiten:
            return maybe_host(Uk) * np.sqrt(self.n_samples_ - 1)
        from dask_ml_tpu.config import get_config

        if get_config()["device_outputs"]:
            # stay on device end to end — np.asarray(S) would force the
            # host sync the device_outputs scope exists to avoid
            return maybe_host(Uk * S[:k])
        return np.asarray(Uk) * np.asarray(S)[:k]

    # -- inference ---------------------------------------------------------

    def transform(self, X):
        X = check_array(X)
        from dask_ml_tpu.config import get_config
        from dask_ml_tpu.parallel import precision as precision_lib

        # wire staging + one jitted program per shape bucket + HOST-side
        # unpad: a repeat transform whose n lands in a warm bucket
        # compiles nothing (the serving-path contract, docs/serving.md)
        Xs, n = shard_rows(X, dtype=precision_lib.staging_wire_dtype())
        out = transform_program(
            Xs, jnp.asarray(self.mean_), jnp.asarray(self.components_),
            jnp.asarray(self.explained_variance_),
            whiten=bool(self.whiten))
        if get_config()["device_outputs"]:
            # whitening divides by a variance that can be zero: the output
            # can be non-finite for FINITE input, so it must keep the
            # downstream NaN scan (trusted=False) — host-path error
            # semantics preserved
            return maybe_host(unpad_rows(out, n), trusted=not self.whiten)
        return np.asarray(out)[:n]

    def inverse_transform(self, X):
        X = check_array(X)
        Xs, n = shard_rows(X)
        comps = jnp.asarray(self.components_)
        if self.whiten:
            comps = jnp.sqrt(jnp.asarray(
                self.explained_variance_))[:, None] * comps
        out = Xs @ comps + jnp.asarray(self.mean_)
        return maybe_host(unpad_rows(out, n))

    # -- Probabilistic-PCA scoring (reference: pca.py:387-434) -------------

    def _scaled_components(self):
        """Components rescaled when whitening, as sklearn's _BasePCA does for
        the covariance/precision model (the reference inherits these)."""
        comps = self.components_.astype(np.float64)
        if self.whiten:
            comps = comps * np.sqrt(
                self.explained_variance_.astype(np.float64))[:, None]
        return comps

    def get_covariance(self):
        """Model covariance C = Vᵀ·diag(λ−σ²)·V + σ²·I (sklearn/_BasePCA
        semantics, which the reference inherits by subclassing)."""
        comps = self._scaled_components()
        exp_var_diff = np.maximum(
            self.explained_variance_ - self.noise_variance_, 0.0)
        cov = (comps.T * exp_var_diff) @ comps
        cov += self.noise_variance_ * np.eye(self.n_features_, dtype=cov.dtype)
        return cov

    def get_precision(self):
        """Inverse model covariance via Woodbury on the small k×k system."""
        n_features = self.n_features_
        if self.n_components_ == 0:
            return np.eye(n_features) / self.noise_variance_
        comps = self._scaled_components()
        exp_var = self.explained_variance_.astype(np.float64)
        if self.noise_variance_ == 0.0:
            return np.linalg.inv(self.get_covariance().astype(np.float64))
        exp_var_diff = np.maximum(exp_var - self.noise_variance_, 0.0)
        small = (comps @ comps.T) / self.noise_variance_
        small[np.diag_indices(len(small))] += 1.0 / np.maximum(
            exp_var_diff, 1e-300)
        out = -(comps.T @ np.linalg.inv(small) @ comps)
        out /= self.noise_variance_ ** 2
        out[np.diag_indices(n_features)] += 1.0 / self.noise_variance_
        return out

    def score_samples(self, X):
        """Per-sample PPCA log-likelihood (reference: pca.py:387-413) —
        the quadratic form runs sharded on device."""
        X = check_array(X)
        Xs, n = shard_rows(X)
        precision = jnp.asarray(self.get_precision(), Xs.dtype)
        Xr = Xs - jnp.asarray(self.mean_)
        ll = -0.5 * (Xr * (Xr @ precision)).sum(axis=1)
        sign, logdet = np.linalg.slogdet(self.get_precision())
        ll = ll - 0.5 * (self.n_features_ * np.log(2.0 * np.pi) - logdet)
        return maybe_host(unpad_rows(ll, n))

    def score(self, X, y=None):
        return float(np.mean(self.score_samples(X)))
