"""Matrix decompositions on sharded tall-skinny data
(reference: decomposition/ — PCA pca.py, TruncatedSVD truncated_svd.py)."""

from dask_ml_tpu.decomposition.pca import PCA  # noqa: F401
from dask_ml_tpu.decomposition.truncated_svd import TruncatedSVD  # noqa: F401

__all__ = ["PCA", "TruncatedSVD"]
