"""Truncated SVD (LSA) on row-sharded arrays, no centering
(reference: decomposition/truncated_svd.py:142-224)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from sklearn.base import BaseEstimator, TransformerMixin

from dask_ml_tpu.config import maybe_host
from dask_ml_tpu.ops import linalg
from dask_ml_tpu.parallel import mesh as mesh_lib
from dask_ml_tpu.parallel.sharding import prepare_data, shard_rows, unpad_rows
from dask_ml_tpu.utils.validation import check_array, check_random_state


class TruncatedSVD(BaseEstimator, TransformerMixin):
    """Dimensionality reduction via truncated SVD without centering.

    ``algorithm``: 'tsqr' (exact distributed QR-SVD then truncate —
    reference: truncated_svd.py:163-167) or 'randomized' (compressed SVD with
    ``n_iter`` power iterations — reference: truncated_svd.py:168-171).
    """

    def __init__(self, n_components=2, algorithm="tsqr", n_iter=5,
                 random_state=None, tol=0.0):
        self.algorithm = algorithm
        self.n_components = n_components
        self.n_iter = n_iter
        self.random_state = random_state
        self.tol = tol

    def _check_array(self, X):
        X = check_array(X)
        if self.n_components >= X.shape[1]:
            raise ValueError(
                "n_components must be < n_features; "
                f"got {self.n_components} >= {X.shape[1]}"
            )
        if self.n_components > X.shape[0]:
            # same guard PCA applies (pca.py): beyond n_samples the extra
            # directions would be zero-singular-value padding artifacts
            raise ValueError(
                "n_components must be <= n_samples; "
                f"got {self.n_components} > {X.shape[0]}"
            )
        return X

    def fit(self, X, y=None):
        self.fit_transform(X)
        return self

    def fit_transform(self, X, y=None):
        X = self._check_array(X)
        if self.algorithm not in {"tsqr", "randomized"}:
            raise ValueError(
                f"algorithm must be 'tsqr' or 'randomized', "
                f"got {self.algorithm!r}"
            )
        k = int(self.n_components)
        mesh = mesh_lib.default_mesh()
        data = prepare_data(X, mesh=mesh)
        if self.algorithm == "tsqr":
            u, s, v = linalg.tsvd(data.X, mesh=mesh, weights=data.weights)
            u, s, v = u[:, :k], s[:k], v[:k]
        else:
            key = check_random_state(self.random_state)
            # bucket the sketch rank to a 32-multiple so an n_components
            # sweep shares one compiled program (same rationale as
            # PCA._fit; the surplus components are sliced off below)
            k_fit = min(-(-k // 32) * 32, min(int(X.shape[0]),
                                              int(X.shape[1])))
            u, s, v = linalg.svd_compressed(
                data.X, k_fit, n_power_iter=int(self.n_iter), key=key,
                mesh=mesh, weights=data.weights)
            u, s, v = u[:, :k], s[:k], v[:k]
        u, v = linalg.svd_flip(u, v)

        X_transformed = u * s
        # Variance bookkeeping on the *valid* rows (reference:
        # truncated_svd.py:174-177 computes both with X.var/ddof=0).
        Xt_valid = unpad_rows(X_transformed, data.n)
        explained_var = np.asarray(jnp.var(Xt_valid, axis=0))
        full_var = float(
            jnp.var(unpad_rows(data.X, data.n), axis=0).sum())
        self.components_ = np.asarray(v)
        self.explained_variance_ = explained_var
        self.explained_variance_ratio_ = explained_var / full_var
        self.singular_values_ = np.asarray(s)
        return np.asarray(Xt_valid)

    def transform(self, X, y=None):
        X = check_array(X)
        Xs, n = shard_rows(X)
        out = Xs @ jnp.asarray(self.components_).T
        return maybe_host(unpad_rows(out, n))

    def inverse_transform(self, X):
        X = check_array(X)
        Xs, n = shard_rows(X)
        out = Xs @ jnp.asarray(self.components_)
        return maybe_host(unpad_rows(out, n))
