"""PCA over data larger than device memory: streamed covariance accumulation.

The blueprint's PCA config is 1e7×1000 — 40 GB of f32, over a single chip's
HBM (VERDICT r3 #3), and the reference's answer (dask chunks spilling to
cluster RAM) has no single-chip analogue. The TPU-native answer for
tall-skinny PCA: one ``lax.scan`` over row blocks accumulating the O(d²)
sufficient statistics (weighted count, column sums, Gram matrix — 4 MB at
d=1000), then an eigendecomposition of the d×d covariance. One pass over
the data, peak HBM = one block + the Gram, exact covariance PCA.

``block_fn(b) -> (X_b, w_b)`` is traced inside the scan: it can regenerate
blocks from a seed (nothing ever resident), pull host-pinned rows via
``jax.pure_callback``, or slice a resident array (tests). Numerical note:
the Gram squares the condition number, so tiny trailing eigenvalues carry
~cond²·eps relative error — the same regime where the in-memory exact path
falls back to Householder. For the top-k components of tall-skinny data
(the PCA use case) f32 Gram accumulation matches the in-memory solver to
test tolerance.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["streamed_moments", "pca_fit_blocks"]


@partial(jax.jit, static_argnames=("block_fn", "n_blocks"))
def streamed_moments(*, block_fn, n_blocks):
    """One scan over all blocks → ``(sw, sums, gram)``:
    Σw, Σ w·x (d,), Σ w·xxᵀ (d, d) — f32 accumulation."""

    def body(carry, b):
        sw, s, G = carry
        X_b, w_b = block_fn(b)
        Xw = X_b * w_b[:, None]
        sw = sw + jnp.sum(w_b)
        s = s + jnp.sum(Xw, axis=0)
        G = G + jax.lax.dot_general(
            Xw, X_b, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return (sw, s, G), None

    shapes = jax.eval_shape(block_fn, jnp.asarray(0, jnp.int32))
    d = shapes[0].shape[1]
    init = (jnp.asarray(0.0, jnp.float32), jnp.zeros((d,), jnp.float32),
            jnp.zeros((d, d), jnp.float32))
    (sw, s, G), _ = jax.lax.scan(
        body, init, jnp.arange(n_blocks, dtype=jnp.int32))
    return sw, s, G


@jax.jit
def _pca_from_moments(sw, s, G):
    mean = s / jnp.maximum(sw, 1.0)
    denom = jnp.maximum(sw - 1.0, 1.0)
    cov = (G - sw * jnp.outer(mean, mean)) / denom
    evals, evecs = jnp.linalg.eigh(cov)  # ascending
    evals = evals[::-1]
    comps = evecs[:, ::-1].T  # (d, d) rows = components, descending
    # deterministic signs (the svd_flip convention): the max-|coeff| entry
    # of every component is positive
    idx = jnp.argmax(jnp.abs(comps), axis=1)
    signs = jnp.sign(comps[jnp.arange(comps.shape[0]), idx])
    comps = comps * jnp.where(signs == 0, 1.0, signs)[:, None]
    return mean, jnp.maximum(evals, 0.0), comps


def pca_fit_blocks(block_fn, n_blocks, n_components, pca=None):
    """Fit a :class:`dask_ml_tpu.decomposition.PCA` from streamed blocks.

    Returns a fitted PCA estimator (components_, explained_variance_ and
    friends populated from the streamed covariance), usable for
    ``transform``/``inverse_transform`` exactly like an in-memory fit.
    ``pca`` optionally supplies a pre-configured estimator to fill in.
    """
    from dask_ml_tpu.decomposition import PCA

    sw, s, G = streamed_moments(block_fn=block_fn, n_blocks=int(n_blocks))
    mean, evals, comps = _pca_from_moments(sw, s, G)
    mean, evals, comps, sw = jax.device_get((mean, evals, comps, sw))

    n = int(round(float(sw)))
    d = comps.shape[1]
    k = int(n_components)
    est = pca if pca is not None else PCA(n_components=k)
    est.n_components_ = k
    est.n_samples_ = n
    est.n_features_ = d
    est.mean_ = np.asarray(mean)
    est.components_ = np.asarray(comps[:k])
    est.explained_variance_ = np.asarray(evals[:k])
    total_var = float(evals.sum())
    est.explained_variance_ratio_ = est.explained_variance_ / max(
        total_var, np.finfo(np.float32).tiny)
    est.singular_values_ = np.sqrt(
        np.maximum(est.explained_variance_ * max(n - 1, 1), 0.0))
    if k < min(n, d):
        est.noise_variance_ = float(evals[k:].mean())
    else:
        est.noise_variance_ = 0.0
    return est
