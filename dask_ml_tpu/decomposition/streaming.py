"""PCA over data larger than device memory: streamed covariance accumulation.

The blueprint's PCA config is 1e7×1000 — 40 GB of f32, over a single chip's
HBM (VERDICT r3 #3), and the reference's answer (dask chunks spilling to
cluster RAM) has no single-chip analogue. The TPU-native answer for
tall-skinny PCA: one ``lax.scan`` over row blocks accumulating the O(d²)
sufficient statistics (weighted count, column sums, Gram matrix — 4 MB at
d=1000), then an eigendecomposition of the d×d covariance. One pass over
the data, peak HBM = one block + the Gram, exact covariance PCA.

``block_fn(b) -> (X_b, w_b)`` is either traced inside the scan — it can
regenerate blocks from a seed (nothing ever resident) or slice a resident
array (tests) — or a :class:`dask_ml_tpu.parallel.stream.HostBlockSource`
streaming real host-resident blocks through the double-buffered transfer
pipeline (block b+1's ``device_put`` overlaps block b's Gram matmul; both
modes accumulate through one shared per-block step, so their moments are
identical). Numerical note:
the Gram squares the condition number, so tiny trailing eigenvalues carry
~cond²·eps relative error — the same regime where the in-memory exact path
falls back to Householder. For the top-k components of tall-skinny data
(the PCA use case) f32 Gram accumulation matches the in-memory solver to
test tolerance.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from dask_ml_tpu.parallel import precision

__all__ = ["streamed_moments", "pca_fit_blocks"]


def _accumulate_block(carry, X_b, w_b):
    """One block's moment update — the single implementation both
    block-source modes run (traced scan and host-streamed driver).

    The carry holds Neumaier compensation terms next to the column-sum and
    Gram accumulators (``precision.neumaier_add``): the streamed tier may
    deliver MANY low-precision blocks (bf16 wire policy,
    docs/precision.md), and a plain f32 running sum over a long block
    chain drifts like O(n_blocks·eps) — the compensated pair holds the
    error at O(eps) regardless of block count. Low-precision blocks upcast
    once on device: accumulation is the accuracy-critical half of the
    moment pass (the wire bytes were already halved host-side)."""
    sw, s, cs, G, cG = carry
    Xf = X_b.astype(jnp.float32)
    Xw = Xf * w_b[:, None]
    sw = sw + jnp.sum(w_b)
    s, cs = precision.neumaier_add(s, cs, jnp.sum(Xw, axis=0))
    G, cG = precision.neumaier_add(G, cG, jax.lax.dot_general(
        Xw, Xf, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32))
    return sw, s, cs, G, cG


def _moments_init(d):
    return (jnp.asarray(0.0, jnp.float32),
            jnp.zeros((d,), jnp.float32), jnp.zeros((d,), jnp.float32),
            jnp.zeros((d, d), jnp.float32), jnp.zeros((d, d), jnp.float32))


def _moments_finalize(carry):
    """Fold the compensation terms in: ``(sw, s, G)`` — the public moment
    contract is unchanged by the compensated carry."""
    sw, s, cs, G, cG = carry
    return sw, s + cs, G + cG


@partial(jax.jit, static_argnames=("block_fn", "n_blocks"))
def _streamed_moments_device(*, block_fn, n_blocks):
    def body(carry, b):
        X_b, w_b = block_fn(b)
        return _accumulate_block(carry, X_b, w_b), None

    shapes = jax.eval_shape(block_fn, jnp.asarray(0, jnp.int32))
    init = _moments_init(shapes[0].shape[1])
    carry, _ = jax.lax.scan(
        body, init, jnp.arange(n_blocks, dtype=jnp.int32))
    return _moments_finalize(carry)


@partial(jax.jit, static_argnames=("transform",))
def _moments_step(carry, blk, *, transform):
    if transform is not None:
        blk = transform(blk)
    X_b, w_b = blk
    return _accumulate_block(carry, X_b, w_b)


def _streamed_moments_host(source, checkpoint_path=None,
                           checkpoint_every=None, elastic=None):
    """Host-driven accumulation over a ``HostBlockSource``: block b+1's
    transfer overlaps block b's Gram matmul (depth = ``source.prefetch``;
    0 = the strict serial overlap-off baseline).

    With ``checkpoint_path`` the single pass is preemption-safe: the carry
    IS the moment accumulators, so a snapshot after block b resumes at
    block b+1 with bit-identical sums (``tests/test_faults.py``)."""
    from dask_ml_tpu.parallel import telemetry
    from dask_ml_tpu.parallel.stream import prefetched_scan

    d = source.out_struct[0].shape[1]

    def step(carry, b, blk):
        carry = _moments_step(carry, blk, transform=source.transform)
        return carry, None

    from dask_ml_tpu.parallel.faults import scan_checkpoint_scope

    carry0, start_block = _moments_init(d), 0
    with telemetry.span("pca.streamed-moments", n_blocks=source.n_blocks,
                        d=int(d)):
        with scan_checkpoint_scope(
                checkpoint_path,
                every=(source.n_blocks if checkpoint_every is None
                       else int(checkpoint_every)),
                bind={"what": "streamed_moments",
                      "n_blocks": source.n_blocks,
                      "d": int(d),
                      # an elastic snapshot has no moments carry (the
                      # published blocks ARE the progress) — resuming it
                      # through the single-host carry path must be a loud
                      # bind error
                      "elastic": elastic is not None,
                      # carry layout version: v2 added the Neumaier
                      # compensation terms — a v1 snapshot must error
                      # loudly, not resume into a different tree structure
                      "carry_v": 2}) as scan_ckpt:
            if elastic is not None:
                # the multi-host sharded pass: per-block moments published
                # to the shared workdir, survivors rebalance a lost host's
                # blocks, every host folds in canonical block-id order
                # (parallel/elastic.py; docs/robustness.md)
                from dask_ml_tpu.parallel.elastic import elastic_moments_host

                return elastic_moments_host(elastic, source,
                                            scan_checkpoint=scan_ckpt)
            if scan_ckpt is not None:
                snap = scan_ckpt.load()
                if snap is not None:
                    carry, _outs, start_block, _epoch = snap
                    carry0 = tuple(jnp.asarray(t) for t in carry)
            carry, _ = prefetched_scan(step, carry0, source,
                                       checkpoint=scan_ckpt,
                                       start_block=start_block)
        if scan_ckpt is not None:
            scan_ckpt.delete()
        return _moments_finalize(carry)


def streamed_moments(*, block_fn, n_blocks, checkpoint_path=None,
                     checkpoint_every=None, elastic=None):
    """One pass over all blocks → ``(sw, sums, gram)``:
    Σw, Σ w·x (d,), Σ w·xxᵀ (d, d) — f32 accumulation, Neumaier-compensated
    across blocks (low-precision blocks upcast on device; see
    ``docs/precision.md``). ``block_fn`` is a
    traced callable (one compiled scan) or a
    :class:`~dask_ml_tpu.parallel.stream.HostBlockSource` (double-buffered
    host streaming); both run :func:`_accumulate_block` per block, so the
    moments are identical across modes.

    ``checkpoint_path``/``checkpoint_every`` (host-source mode only) make
    the pass preemption-safe — snapshots every k blocks, SIGTERM-driven
    graceful drain, resume from the last complete block; see
    ``docs/robustness.md``.

    ``elastic`` (an :class:`~dask_ml_tpu.parallel.elastic.ElasticRun`,
    host-source mode only) shards the pass over a fleet of processes:
    each host computes and publishes its shard's per-block moments,
    survivors rebalance a lost host's blocks, and every host folds the
    published moments in canonical block-id order — elastic results are
    bit-identical across rosters/deaths/resumes and match this
    single-host path to Neumaier accuracy (``docs/robustness.md``
    "Elastic epochs")."""
    from dask_ml_tpu.parallel.stream import HostBlockSource

    if isinstance(block_fn, HostBlockSource):
        if block_fn.n_blocks != int(n_blocks):
            raise ValueError(
                f"n_blocks={n_blocks} does not match the HostBlockSource's "
                f"{block_fn.n_blocks} blocks")
        return _streamed_moments_host(block_fn, checkpoint_path,
                                      checkpoint_every, elastic=elastic)
    if checkpoint_path is not None:
        raise ValueError(
            "checkpoint_path= requires a HostBlockSource: a traced "
            "block_fn runs the whole pass as one compiled scan")
    if elastic is not None:
        raise ValueError(
            "elastic= requires a HostBlockSource: the elastic data plane "
            "shards host-resident block INGESTION across processes — a "
            "traced block_fn has no host blocks to shard")
    return _streamed_moments_device(block_fn=block_fn, n_blocks=int(n_blocks))


@jax.jit
def _pca_from_moments(sw, s, G):
    mean = s / jnp.maximum(sw, 1.0)
    denom = jnp.maximum(sw - 1.0, 1.0)
    cov = (G - sw * jnp.outer(mean, mean)) / denom
    evals, evecs = jnp.linalg.eigh(cov)  # ascending
    evals = evals[::-1]
    comps = evecs[:, ::-1].T  # (d, d) rows = components, descending
    # deterministic signs (the svd_flip convention): the max-|coeff| entry
    # of every component is positive
    idx = jnp.argmax(jnp.abs(comps), axis=1)
    signs = jnp.sign(comps[jnp.arange(comps.shape[0]), idx])
    comps = comps * jnp.where(signs == 0, 1.0, signs)[:, None]
    return mean, jnp.maximum(evals, 0.0), comps


def pca_fit_blocks(block_fn, n_blocks, n_components, pca=None,
                   checkpoint_path=None, checkpoint_every=None,
                   elastic=None):
    """Fit a :class:`dask_ml_tpu.decomposition.PCA` from streamed blocks.

    Returns a fitted PCA estimator (components_, explained_variance_ and
    friends populated from the streamed covariance), usable for
    ``transform``/``inverse_transform`` exactly like an in-memory fit.
    ``pca`` optionally supplies a pre-configured estimator to fill in.
    ``checkpoint_path``/``checkpoint_every`` (host-source mode) make the
    moment pass preemption-safe, and ``elastic`` shards it over a fleet
    with survivor rebalancing — see :func:`streamed_moments`.
    """
    from dask_ml_tpu.decomposition import PCA

    sw, s, G = streamed_moments(block_fn=block_fn, n_blocks=int(n_blocks),
                                checkpoint_path=checkpoint_path,
                                checkpoint_every=checkpoint_every,
                                elastic=elastic)
    mean, evals, comps = _pca_from_moments(sw, s, G)
    mean, evals, comps, sw = jax.device_get((mean, evals, comps, sw))

    n = int(round(float(sw)))
    d = comps.shape[1]
    k = int(n_components)
    est = pca if pca is not None else PCA(n_components=k)
    est.n_components_ = k
    est.n_samples_ = n
    est.n_features_ = d
    est.mean_ = np.asarray(mean)
    est.components_ = np.asarray(comps[:k])
    est.explained_variance_ = np.asarray(evals[:k])
    total_var = float(evals.sum())
    est.explained_variance_ratio_ = est.explained_variance_ / max(
        total_var, np.finfo(np.float32).tiny)
    est.singular_values_ = np.sqrt(
        np.maximum(est.explained_variance_ * max(n - 1, 1), 0.0))
    if k < min(n, d):
        est.noise_variance_ = float(evals[k:].mean())
    else:
        est.noise_variance_ = 0.0
    return est
