"""XGBoost hand-off (reference: xgboost.py:1-7 re-exports ``dask-xgboost``).

The reference trains distributed XGBoost on the dask cluster's workers via
rabit. A TPU mesh is not an XGBoost runtime, so the parity surface is the
hand-off: export the (possibly TPU-resident, sharded) features to host and
feed xgboost's own trainer::

    from dask_ml_tpu.xgboost import to_numpy
    import xgboost as xgb
    dtrain = xgb.DMatrix(to_numpy(Xd), label=to_numpy(yd))
    booster = xgb.train(params, dtrain)

``to_numpy`` drops the mesh-padding rows, so labels stay aligned.
"""

from dask_ml_tpu.interop import export_learned_attrs, to_numpy  # noqa: F401
