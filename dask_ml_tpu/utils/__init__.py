"""Shared utilities: validation, PRNG handling, test helpers.

Parity with the reference's L2 layer (reference: dask_ml/utils.py,
_utils.py, _compat.py).
"""

from dask_ml_tpu.utils._log import (  # noqa: F401
    format_bytes,
    log_array,
    profile_phase,
)
from dask_ml_tpu.utils._utils import (  # noqa: F401
    check_chunks,
    copy_learned_attributes,
    handle_zeros_in_scale,
    slice_columns,
)
from dask_ml_tpu.utils.validation import svd_flip  # noqa: F401
from dask_ml_tpu.utils.validation import (  # noqa: F401
    check_array,
    check_random_state,
    check_random_state_np,
    row_norms,
)
from dask_ml_tpu.utils.testing import assert_estimator_equal  # noqa: F401
