"""Test helpers — primarily :func:`assert_estimator_equal`, the differential
oracle used throughout the suite (reference: utils.py:51-79, the dominant test
technique per its test suite, e.g. tests/test_kmeans.py:59-89)."""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp


def _to_host(x):
    if isinstance(x, jax.Array):
        return np.asarray(x)
    return x


def _assert_eq(a, b, name: str, rtol: float, atol: float):
    a, b = _to_host(a), _to_host(b)
    if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
        np.testing.assert_allclose(
            np.asarray(a, dtype=np.float64),
            np.asarray(b, dtype=np.float64),
            rtol=rtol,
            atol=atol,
            err_msg=f"attribute {name!r} differs",
        )
    elif isinstance(a, (float, np.floating)) or isinstance(b, (float, np.floating)):
        np.testing.assert_allclose(float(a), float(b), rtol=rtol, atol=atol,
                                   err_msg=f"attribute {name!r} differs")
    elif isinstance(a, dict):
        assert set(a) == set(b), f"attribute {name!r}: dict keys differ"
        for k in a:
            _assert_eq(a[k], b[k], f"{name}[{k!r}]", rtol, atol)
    else:
        assert a == b, f"attribute {name!r}: {a!r} != {b!r}"


def assert_estimator_equal(
    left,
    right,
    exclude=(),
    rtol: float = 1e-4,
    atol: float = 1e-4,
):
    """Check that two fitted estimators agree on every learned
    (trailing-underscore) attribute, up to tolerance.

    Mirrors the reference helper's semantics (same attribute discovery rule,
    recursive array/dict comparison), with looser default tolerances because
    our side computes in float32 on the accelerator.
    """
    exclude = set([exclude] if isinstance(exclude, str) else exclude)
    left_attrs = {
        a for a in dir(left) if a.endswith("_") and not a.startswith("_")
    } - exclude
    right_attrs = {
        a for a in dir(right) if a.endswith("_") and not a.startswith("_")
    } - exclude
    assert left_attrs == right_attrs, (
        f"Estimators have different fitted attributes: "
        f"only-left={sorted(left_attrs - right_attrs)} "
        f"only-right={sorted(right_attrs - left_attrs)}"
    )
    for attr in sorted(left_attrs):
        l, r = getattr(left, attr), getattr(right, attr)
        _assert_eq(l, r, attr, rtol, atol)
