"""Learned-attribute copying (reference: dask_ml/_utils.py:1-5)."""

from __future__ import annotations


def copy_learned_attributes(from_estimator, to_estimator) -> None:
    """Copy every fitted (trailing-underscore) attribute from one estimator
    to another, preserving the sklearn convention that learned state lives in
    ``*_`` attributes."""
    attrs = {
        k: v
        for k, v in vars(from_estimator).items()
        if k.endswith("_") and not k.startswith("_")
    }
    for k, v in attrs.items():
        setattr(to_estimator, k, v)
