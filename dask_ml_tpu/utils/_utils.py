"""Small L2 helpers (reference: dask_ml/_utils.py, dask_ml/utils.py)."""

from __future__ import annotations


def slice_columns(X, columns):
    """Column subset for frame-likes, pass-through for arrays
    (reference: utils.py:147-151 — it slices dask DataFrames only; arrays
    pass through untouched, and so do they here)."""
    if hasattr(X, "iloc"):  # pandas frame
        return X[list(X.columns) if columns is None else list(columns)]
    return X


def check_chunks(n_samples: int, n_features: int, chunks=None) -> tuple:
    """Validate/normalize a row-partition request
    (reference: utils.py:177-214).

    The reference picks dask chunk sizes (one block per CPU core, >= 100
    rows each); the mesh analogue is rows-per-shard over the data axis —
    same signature and return convention ``(rows_per_block, n_features)``,
    with the device count standing in for the core count. The staging layer
    (``parallel.sharding``) doesn't need this — shards are always even —
    but host-side block loops (``Incremental``-style streaming) use it to
    pick a block size.
    """
    from collections.abc import Sequence
    from numbers import Integral

    import jax

    if chunks is None:
        chunks = (max(100, n_samples // jax.device_count()), n_features)
    elif isinstance(chunks, Integral):
        chunks = (max(100, n_samples // int(chunks)), n_features)
    elif isinstance(chunks, Sequence) and not isinstance(chunks, str):
        chunks = tuple(chunks)
        if len(chunks) != 2:
            raise AssertionError("Chunks should be a 2-tuple.")
    else:
        raise ValueError(f"Unknown type of chunks: '{type(chunks)}'")
    return chunks


def handle_zeros_in_scale(scale):
    """Zero scales mean constant features: divide by 1 instead
    (reference: utils.py:154-161)."""
    import numpy as np

    scale = np.asarray(scale, dtype=float).copy()
    scale[scale == 0.0] = 1.0
    return scale


def copy_learned_attributes(from_estimator, to_estimator) -> None:
    """Copy every fitted (trailing-underscore) attribute from one estimator
    to another, preserving the sklearn convention that learned state lives in
    ``*_`` attributes."""
    attrs = {
        k: v
        for k, v in vars(from_estimator).items()
        if k.endswith("_") and not k.startswith("_")
    }
    for k, v in attrs.items():
        setattr(to_estimator, k, v)
