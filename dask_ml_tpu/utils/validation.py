"""Input validation and PRNG handling.

The TPU analogue of the reference's dask-aware ``check_array``
(reference: utils.py:95-143) and ``check_random_state``
(reference: utils.py:164-174, which returns a ``da.random.RandomState``).
Here validation happens on the host array before staging to the mesh, and
randomness is a ``jax.random`` key so every jitted kernel is reproducible and
splittable per shard.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np


def check_array(
    X,
    *,
    ensure_2d: bool = True,
    allow_nd: bool = False,
    force_all_finite: bool = True,
    dtype: Optional[jnp.dtype] = None,
    min_samples: int = 1,
    accept_sparse: bool = False,
):
    """Validate an input array and return it staging-ready.

    Host (numpy/list) inputs validate entirely host-side and come back as
    host numpy — the staging layer owns the single host→device transfer,
    and no per-shape device program (the old jitted finite-scan compiled
    once per distinct ``(n, d)``) ever runs for them. Device
    (``jax.Array``) inputs keep the fused on-device scan, so
    ``device_outputs`` pipelines never materialize to host here.

    ``accept_sparse=True`` (the sparse-capable callers: GLMs, the sparse
    scaler/encoder, the search prep) passes scipy CSR through WITHOUT
    densifying: validation runs over ``.data`` only (finiteness, dtype
    coercion — indices are exact ints and stay untouched), in O(nnz).
    CSC — column-major, the wrong layout for sample-axis sharding — is
    rejected with the conversion spelled out rather than silently
    transposed or densified. The default (``False``) keeps the loud
    dense-only error for estimators whose kernels have no sparse path.

    Dtype policy (TPU-first): integer and float64 inputs are converted to
    float32 unless an explicit ``dtype`` is given — the reference similarly
    upcasts ints to float for KMeans (reference: cluster/k_means.py:147-152),
    but we *down*-cast doubles because f32/bf16 is the native TPU regime.
    """
    if hasattr(X, "iloc"):  # pandas — reject like the reference's KMeans path
        raise TypeError(
            "DataFrame inputs are not supported here; pass .values "
            "(reference rejects dask.dataframe the same way, "
            "cluster/k_means.py:153-160)"
        )
    # Inside a staging_memo scope (the search driver), validation of the
    # same source object is done once: it involves a host→device transfer
    # and a finiteness sync, both worth sharing across candidates.
    from dask_ml_tpu.parallel.sharding import _current_memo

    memo = _current_memo()
    if memo is not None:
        return memo.get_or_stage(
            ("check", id(X), ensure_2d, allow_nd, force_all_finite,
             str(dtype), min_samples, accept_sparse),
            (X,),
            lambda: _check_array_impl(X, ensure_2d, allow_nd,
                                      force_all_finite, dtype, min_samples,
                                      accept_sparse),
        )
    return _check_array_impl(X, ensure_2d, allow_nd, force_all_finite, dtype,
                             min_samples, accept_sparse)


def staging_dtype(np_dtype):
    """The TPU-first dtype policy for staging untyped numeric input:
    ints/uints/bools → float32; float64 → float32 unless x64 is enabled;
    f32/f16/bf16 kept (returns ``None`` = no conversion). One definition so
    every staging path (check_array, the search driver's device CV slices)
    applies identical coercion."""
    kind = np.dtype(np_dtype).kind
    if kind in "iub":
        return jnp.float32
    if (kind == "f" and np.dtype(np_dtype).itemsize > 4
            and not jax.config.jax_enable_x64):
        return jnp.float32
    return None


def _check_array_impl(X, ensure_2d, allow_nd, force_all_finite, dtype,
                      min_samples, accept_sparse=False):
    import scipy.sparse

    from dask_ml_tpu.ops.sparse import SparseRows

    if isinstance(X, SparseRows):
        # an already-encoded sparse container (our own encoders, or
        # user-built): validated like every other input — dtype coercion
        # and finiteness run over the VALUES leaf only, O(nnz)
        if not accept_sparse:
            raise TypeError(
                "this estimator has no sparse kernel path; SparseRows "
                "containers are accepted by the GLMs, StandardScaler"
                "(with_mean=False), and OneHotEncoder (docs/sparse.md)")
        if X.shape[0] < min_samples:
            raise ValueError(
                f"Found array with {X.shape[0]} sample(s) while a minimum "
                f"of {min_samples} is required")
        vals = X.values
        if int(np.prod(X.cols.shape)):
            # structural validity of the indices leaf: an out-of-range
            # column would not raise downstream — XLA gathers clamp and
            # segment_sum drops — silently fitting wrong coefficients.
            # Host leaves reduce in numpy; device leaves through one
            # fused jitted reduction (two scalars fetched, never the leaf)
            if isinstance(X.cols, np.ndarray):
                cmin, cmax = int(X.cols.min()), int(X.cols.max())
            else:
                cmin, cmax = (int(v) for v in _min_max_scalar(X.cols))
            if cmin < 0 or cmax >= X.d:
                raise ValueError(
                    f"SparseRows column indices must lie in [0, {X.d}); "
                    f"found range [{cmin}, {cmax}]")
        if isinstance(vals, np.ndarray):
            kind = np.dtype(vals.dtype).kind
            if dtype is None:
                if kind not in "fiub":
                    raise ValueError(f"Unsupported dtype {vals.dtype}")
                dtype = staging_dtype(vals.dtype)
            if dtype is not None and vals.dtype != np.dtype(dtype):
                # e.g. an integer-valued OneHotEncoder(dtype=int) output:
                # without the cast, matvec would truncate the f32
                # coefficient vector to the values' integer dtype
                vals = vals.astype(dtype)
            if force_all_finite and np.dtype(vals.dtype).kind == "f":
                try:
                    finite = bool(np.isfinite(vals).all())
                except TypeError:  # exotic float without ufunc support
                    finite = bool(np.isfinite(
                        vals.astype(np.float32, copy=False)).all())
                if not finite:
                    raise ValueError("Input contains NaN or infinity")
            if vals is X.values:
                return X
            return SparseRows(vals, X.cols, X.d)
        # device-staged container (scaler output, staged data): coerce
        # low-precision-safe dtype and keep the fused finite scan
        if dtype is None and jnp.dtype(vals.dtype).kind in "iub":
            vals = vals.astype(jnp.float32)
        elif dtype is not None and vals.dtype != jnp.dtype(dtype):
            vals = vals.astype(dtype)
        if force_all_finite and jnp.dtype(vals.dtype).kind == "f":
            if not bool(_all_finite(vals)):
                raise ValueError("Input contains NaN or infinity")
        if vals is X.values:
            return X
        return SparseRows(vals, X.cols, X.d)
    if scipy.sparse.issparse(X):
        if not accept_sparse:
            # np.asarray on a scipy matrix yields a 0-d object array and a
            # baffling downstream crash; fail with the real story instead
            raise TypeError(
                "scipy.sparse input is not supported by this estimator "
                "(dense device staging only); densify with .toarray(), or "
                "keep a scikit-learn estimator for sparse data — the "
                "search driver and wrappers pass sparse through to foreign "
                "estimators. The GLMs, StandardScaler(with_mean=False) and "
                "OneHotEncoder accept CSR natively (docs/sparse.md)"
            )
        if X.format != "csr":
            raise TypeError(
                f"sparse input must be CSR (row-major — the layout the "
                f"sample-axis sharding and the blocked-ELL wire encoding "
                f"need); got {X.format.upper()}. Convert with X.tocsr() "
                "(an O(nnz) host-side re-index, done once, never a "
                "densify)")
        if X.ndim != 2:  # pragma: no cover - scipy matrices are always 2-D
            raise ValueError(f"Expected 2D sparse matrix, got {X.ndim}D")
        if X.shape[0] < min_samples:
            raise ValueError(
                f"Found array with {X.shape[0]} sample(s) while a minimum "
                f"of {min_samples} is required")
        if X.indices.size and (int(X.indices.min()) < 0
                               or int(X.indices.max()) >= X.shape[1]):
            # scipy's constructor does not bounds-check index CONTENTS;
            # downstream XLA gathers would clamp and segment_sum would
            # drop out-of-range ids — fitting wrong coefficients silently
            raise ValueError(
                f"CSR column indices must lie in [0, {X.shape[1]}); "
                f"found range [{int(X.indices.min())}, "
                f"{int(X.indices.max())}]")
        data = X.data
        kind = np.dtype(data.dtype).kind
        if dtype is None:
            if kind not in "fiub":
                raise ValueError(f"Unsupported dtype {data.dtype}")
            dtype = staging_dtype(data.dtype)
        if dtype is not None and data.dtype != np.dtype(dtype):
            data = data.astype(dtype)
        # finiteness over the NONZEROS only — O(nnz), the whole point of
        # accepting sparse (explicit zeros are finite by construction);
        # post-cast, so a narrowing-cast overflow is still caught
        if force_all_finite and np.dtype(data.dtype).kind == "f":
            if not bool(np.isfinite(data).all()):
                raise ValueError("Input contains NaN or infinity")
        if data is X.data:
            return X
        return scipy.sparse.csr_matrix(
            (data, X.indices, X.indptr), shape=X.shape)
    arr = np.asarray(X) if not isinstance(X, jax.Array) else X
    if ensure_2d and arr.ndim != 2:
        raise ValueError(
            f"Expected 2D array, got {arr.ndim}D array of shape {arr.shape}"
        )
    if not allow_nd and arr.ndim > 2:
        raise ValueError(f"Expected <=2D array, got shape {arr.shape}")
    if arr.shape[0] < min_samples:
        raise ValueError(
            f"Found array with {arr.shape[0]} sample(s) while a minimum of "
            f"{min_samples} is required"
        )
    if dtype is None:
        kind = np.dtype(arr.dtype).kind
        if kind not in "fiub":
            raise ValueError(f"Unsupported dtype {arr.dtype}")
        dtype = staging_dtype(arr.dtype)
    if not isinstance(X, jax.Array):
        # HOST input: validate host-side and return host numpy — the
        # staging layer (shard_rows/prepare_data) owns the one transfer.
        # The former jnp round-trip here cost an extra unsharded upload
        # AND compiled the finite-scan per distinct (n, d): exactly the
        # per-request overhead a predict path serving live traffic cannot
        # pay (docs/serving.md). Cast BEFORE scanning so an overflow the
        # narrowing cast manufactures (1e300 → inf in f32) is still
        # caught, matching the device path's post-cast scan.
        if dtype is not None and arr.dtype != np.dtype(dtype):
            arr = arr.astype(dtype)
        if force_all_finite and np.dtype(arr.dtype).kind == "f":
            try:
                finite = bool(np.isfinite(arr).all())
            except TypeError:  # exotic float (ml_dtypes) without ufunc
                finite = bool(np.isfinite(
                    arr.astype(np.float32, copy=False)).all())
            if not finite:
                raise ValueError("Input contains NaN or infinity")
        return arr
    out = jnp.asarray(arr, dtype=dtype)
    if force_all_finite:
        if isinstance(X, jax.Array):
            from dask_ml_tpu.parallel.sharding import _current_memo

            memo = _current_memo()
            if memo is not None and memo.is_trusted(X):
                # explicitly validated within this staging scope (a CV
                # slice scanned once at upload, or an output derived from
                # validated input): re-scanning would cost a host sync per
                # pipeline stage — the round-trip the scope eliminates.
                # Untrusted device arrays (user-supplied, or slices of
                # non-finite data) still get the scan below.
                return out
        # Single fused reduction — the analogue of the reference's one-pass
        # NaN/inf scan (reference: cluster/k_means.py:161-170). One jitted
        # program, not two eager ops: on this backend every distinct tiny
        # program costs ~0.7s of fixed compile overhead on first touch.
        if not bool(_all_finite(out)):
            raise ValueError("Input contains NaN or infinity")
    return out


@jax.jit
def _all_finite(x):
    return jnp.isfinite(x).all()


@jax.jit
def _min_max_scalar(x):
    return jnp.min(x), jnp.max(x)


KeyArray = jax.Array


def check_random_state(
    seed: Union[None, int, KeyArray, np.random.RandomState] = None,
) -> KeyArray:
    """Coerce ``seed`` into a ``jax.random`` key."""
    if seed is None:
        return jax.random.key(np.random.SeedSequence().entropy % (2**63))
    if isinstance(seed, (int, np.integer)):
        return jax.random.key(int(seed))
    if isinstance(seed, np.random.RandomState):
        return jax.random.key(int(seed.randint(0, 2**31 - 1)))
    if isinstance(seed, jax.Array) and jnp.issubdtype(seed.dtype, jax.dtypes.prng_key):
        return seed
    raise TypeError(f"Cannot coerce {type(seed)!r} into a jax PRNG key")


def check_random_state_np(
    seed: Union[None, int, np.random.RandomState] = None,
) -> np.random.RandomState:
    """NumPy RandomState for host-side components (encoders, sklearn interop)."""
    if isinstance(seed, np.random.RandomState):
        return seed
    return np.random.RandomState(seed)


def row_norms(X, squared: bool = False) -> jax.Array:
    """Per-row L2 norms as one fused reduction (reference: utils.py:44-48,
    which maps sklearn's ``row_norms`` over dask blocks). On TPU this is a
    single jitted row reduction; padding rows (all-zero) get norm 0, so it
    composes with the sharded/padded layout unchanged."""
    X = jnp.asarray(X)
    sq = jnp.sum(X * X, axis=-1)
    return sq if squared else jnp.sqrt(sq)


@partial(jax.jit, static_argnames=("u_based_decision",))
def svd_flip(u, v, u_based_decision: bool = False):
    """Deterministic SVD signs (the reference wraps sklearn's via a delayed
    task, utils.py:18-25). Default is the v-based convention — the max-|v|
    entry of each right singular vector made positive — matching modern
    sklearn (≥1.5) PCA/TruncatedSVD so differential tests compare signed
    components. v-based is also the cheap choice here: v is the small
    replicated factor, so the sign decision involves no sharded reduction."""
    if u_based_decision:
        max_rows = jnp.argmax(jnp.abs(u), axis=0)
        signs = jnp.sign(u[max_rows, jnp.arange(u.shape[1])])
    else:
        max_cols = jnp.argmax(jnp.abs(v), axis=1)
        signs = jnp.sign(v[jnp.arange(v.shape[0]), max_cols])
    signs = jnp.where(signs == 0, 1.0, signs)
    return u * signs[None, :], v * signs[:, None]
