"""Structured array/placement logging and profiler hooks.

The reference logs array geometry at INFO during expensive fits
(reference: utils.py:217-241 ``_log_array`` / ``_format_bytes``; used from
cluster/k_means.py:444-452). The TPU analogue reports what actually matters
here — shape, dtype, host bytes, and the mesh placement (axis layout +
PartitionSpec) — and adds ``jax.profiler`` hooks, which are the platform's
native tracing story (reference's analogue is dask's scheduler dashboards).

Profiling is opt-in two ways:

- :func:`profile_phase` always emits a ``jax.profiler.TraceAnnotation`` so
  phases show up named in any externally-captured trace, and logs wall time
  at DEBUG.
- Setting ``DASK_ML_TPU_PROFILE_DIR=/some/dir`` makes the *outermost*
  :func:`profile_phase` capture a full ``jax.profiler.trace`` into that
  directory (viewable in TensorBoard / xprof) with zero code changes.

:func:`profile_phase` is now a DEPRECATED thin wrapper over the unified
telemetry subsystem's :func:`~dask_ml_tpu.parallel.telemetry.span`
(``span(name, logger=logger)`` — same TraceAnnotation, same DEBUG/INFO log
lines, same env-var outermost-capture contract, plus ring-buffer recording
and metrics when the ``telemetry`` config knob is on). New code should call
``span`` directly; see docs/observability.md for the migration table.
"""

from __future__ import annotations

import logging

__all__ = ["format_bytes", "log_array", "profile_phase"]

PROFILE_DIR_ENV = "DASK_ML_TPU_PROFILE_DIR"


def format_bytes(n: int) -> str:
    """1234 → '1.23 kB' (reference: utils.py:222-241 ``_format_bytes``)."""
    if n > 1e9:
        return "%0.2f GB" % (n / 1e9)
    if n > 1e6:
        return "%0.2f MB" % (n / 1e6)
    if n > 1e3:
        return "%0.2f kB" % (n / 1e3)
    return "%d B" % n


def _placement(x) -> str:
    """Describe where an array lives: mesh axes + PartitionSpec, or host."""
    sharding = getattr(x, "sharding", None)
    mesh = getattr(sharding, "mesh", None)
    if mesh is not None:
        axes = ",".join(
            f"{name}={size}" for name, size in
            zip(mesh.axis_names, mesh.devices.shape)
        )
        return f"mesh({axes}) spec={getattr(sharding, 'spec', None)}"
    if sharding is not None:
        return str(sharding)
    return "host"


def log_array(logger: logging.Logger, name: str, x,
              level: int = logging.INFO) -> None:
    """One structured line: name, shape, dtype, bytes, placement."""
    if not logger.isEnabledFor(level):
        return
    shape = tuple(getattr(x, "shape", ()))
    dtype = getattr(x, "dtype", None)
    nbytes = getattr(x, "nbytes", None)
    if nbytes is None and hasattr(x, "nnz") and hasattr(x, "data"):
        # scipy sparse: report the nnz-based bytes actually held
        # (data + indices + indptr), never the dense n*d*itemsize the
        # shape-derived fallback below would invent — at 0.1% density
        # that fallback overstates by ~250x. (SparseRows containers carry
        # their own nnz-based .nbytes and never reach this branch.)
        nbytes = int(getattr(x.data, "nbytes", 0))
        for attr in ("indices", "indptr", "row", "col", "offsets"):
            arr = getattr(x, attr, None)
            if arr is not None:
                nbytes += int(getattr(arr, "nbytes", 0))
    if nbytes is None and dtype is not None:
        size = 1
        for s in shape:
            size *= int(s)
        # resolve the true itemsize through np.dtype: dtype may be a scalar
        # TYPE (jnp.bfloat16) with no .itemsize attribute, and the old
        # 4-byte guess reported bf16 arrays at 2x their actual size
        try:
            import numpy as np

            itemsize = int(np.dtype(dtype).itemsize)
        except TypeError:
            itemsize = int(getattr(dtype, "itemsize", 4))
        nbytes = size * itemsize
    logger.log(
        level, "%s: shape=%s dtype=%s %s on %s",
        name, shape, dtype,
        format_bytes(int(nbytes)) if nbytes is not None else "?",
        _placement(x),
    )


def profile_phase(logger: logging.Logger, name: str):
    """DEPRECATED alias for
    :func:`dask_ml_tpu.parallel.telemetry.span(name, logger=logger)
    <dask_ml_tpu.parallel.telemetry.span>` — kept so pre-telemetry call
    sites and user code keep working unchanged.

    The contract is byte-for-byte the old one: the phase appears as a
    ``TraceAnnotation`` in any active profiler capture, wall time logs at
    DEBUG, and when ``DASK_ML_TPU_PROFILE_DIR`` is set the outermost phase
    in each thread starts/stops a full ``jax.profiler.trace`` capture into
    that directory (logged at INFO). Additionally — new with the telemetry
    subsystem — the phase records a span when the ``telemetry`` config
    knob is on.
    """
    from dask_ml_tpu.parallel.telemetry import span

    return span(name, logger=logger)
