"""Structured array/placement logging and profiler hooks.

The reference logs array geometry at INFO during expensive fits
(reference: utils.py:217-241 ``_log_array`` / ``_format_bytes``; used from
cluster/k_means.py:444-452). The TPU analogue reports what actually matters
here — shape, dtype, host bytes, and the mesh placement (axis layout +
PartitionSpec) — and adds ``jax.profiler`` hooks, which are the platform's
native tracing story (reference's analogue is dask's scheduler dashboards).

Profiling is opt-in two ways:

- :func:`profile_phase` always emits a ``jax.profiler.TraceAnnotation`` so
  phases show up named in any externally-captured trace, and logs wall time
  at DEBUG.
- Setting ``DASK_ML_TPU_PROFILE_DIR=/some/dir`` makes the *outermost*
  :func:`profile_phase` capture a full ``jax.profiler.trace`` into that
  directory (viewable in TensorBoard / xprof) with zero code changes.
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
import time

__all__ = ["format_bytes", "log_array", "profile_phase"]

PROFILE_DIR_ENV = "DASK_ML_TPU_PROFILE_DIR"


def format_bytes(n: int) -> str:
    """1234 → '1.23 kB' (reference: utils.py:222-241 ``_format_bytes``)."""
    if n > 1e9:
        return "%0.2f GB" % (n / 1e9)
    if n > 1e6:
        return "%0.2f MB" % (n / 1e6)
    if n > 1e3:
        return "%0.2f kB" % (n / 1e3)
    return "%d B" % n


def _placement(x) -> str:
    """Describe where an array lives: mesh axes + PartitionSpec, or host."""
    sharding = getattr(x, "sharding", None)
    mesh = getattr(sharding, "mesh", None)
    if mesh is not None:
        axes = ",".join(
            f"{name}={size}" for name, size in
            zip(mesh.axis_names, mesh.devices.shape)
        )
        return f"mesh({axes}) spec={getattr(sharding, 'spec', None)}"
    if sharding is not None:
        return str(sharding)
    return "host"


def log_array(logger: logging.Logger, name: str, x,
              level: int = logging.INFO) -> None:
    """One structured line: name, shape, dtype, bytes, placement."""
    if not logger.isEnabledFor(level):
        return
    shape = tuple(getattr(x, "shape", ()))
    dtype = getattr(x, "dtype", None)
    nbytes = getattr(x, "nbytes", None)
    if nbytes is None and dtype is not None:
        size = 1
        for s in shape:
            size *= int(s)
        nbytes = size * getattr(dtype, "itemsize", 4)
    logger.log(
        level, "%s: shape=%s dtype=%s %s on %s",
        name, shape, dtype,
        format_bytes(int(nbytes)) if nbytes is not None else "?",
        _placement(x),
    )


_trace_state = threading.local()


@contextlib.contextmanager
def profile_phase(logger: logging.Logger, name: str):
    """Name a fit phase for profiling and log its wall time at DEBUG.

    Inside the scope the phase appears as a ``TraceAnnotation`` in any
    active profiler capture; when ``DASK_ML_TPU_PROFILE_DIR`` is set the
    outermost phase in each thread also starts/stops a full
    ``jax.profiler.trace`` capture into that directory.
    """
    import jax.profiler

    trace_dir = os.environ.get(PROFILE_DIR_ENV)
    own_trace = bool(trace_dir) and not getattr(_trace_state, "active", False)
    if own_trace:
        _trace_state.active = True
        jax.profiler.start_trace(trace_dir)
    t0 = time.perf_counter()
    try:
        with jax.profiler.TraceAnnotation(name):
            yield
    finally:
        dt = time.perf_counter() - t0
        if own_trace:
            jax.profiler.stop_trace()
            _trace_state.active = False
            logger.info("phase %s: %.3fs (trace -> %s)", name, dt, trace_dir)
        else:
            logger.debug("phase %s: %.3fs", name, dt)
