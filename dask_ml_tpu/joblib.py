"""joblib interop (reference: joblib.py:1 registers the distributed joblib
backend as an import side-effect).

No backend registration is needed here: this framework's estimators hold
their learned state as plain host ndarrays after fit, so they pickle with
stock joblib, and sklearn's ``n_jobs``-threaded code can call them directly —
predictions release the GIL during device execution. This module exists for
import parity and documents the equivalence::

    import joblib
    joblib.dump(fitted_estimator, "model.joblib")   # just works
    est = joblib.load("model.joblib")
"""

from dask_ml_tpu.interop import export_learned_attrs, to_numpy  # noqa: F401
