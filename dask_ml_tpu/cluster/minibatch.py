"""Deprecated PartialMiniBatchKMeans wrapper
(reference: cluster/minibatch.py:9-11)."""

from __future__ import annotations

from sklearn.cluster import MiniBatchKMeans as _MiniBatchKMeans

from dask_ml_tpu._partial import _BigPartialFitMixin, _copy_partial_doc


@_copy_partial_doc
class PartialMiniBatchKMeans(_BigPartialFitMixin, _MiniBatchKMeans):
    pass
