"""Mini-batch KMeans over the fused assignment kernel, plus the deprecated
``PartialMiniBatchKMeans`` wrapper (reference: cluster/minibatch.py:9-11).

:class:`MiniBatchKMeans` is the TPU-native streaming variant of
:class:`~dask_ml_tpu.cluster.KMeans` (Sculley 2010 web-scale k-means): each
step draws a batch, assigns it to the nearest centers, and moves each center
toward its batch mean with a per-center learning rate ``1/v_j`` (``v_j`` =
total weight the center has absorbed). The assignment routes through
:func:`~dask_ml_tpu.ops.fused_distance.fused_argmin_min` — the single
implementation of the distance+reduce idiom, so the (batch × k) distance
matrix follows the same fused/XLA dispatch as every other consumer instead
of materializing privately — and the whole multi-step optimization runs as
ONE ``lax.scan`` program on device (no per-batch host round trip).

The deprecated :class:`PartialMiniBatchKMeans` (sklearn's estimator fed
block-wise through the ``_BigPartialFitMixin``) is kept for drop-in parity.
"""

from __future__ import annotations

import logging
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from sklearn.base import BaseEstimator, TransformerMixin
from sklearn.cluster import MiniBatchKMeans as _MiniBatchKMeans

from dask_ml_tpu._partial import _BigPartialFitMixin, _copy_partial_doc
from dask_ml_tpu.config import maybe_host
from dask_ml_tpu.models import kmeans as core
from dask_ml_tpu.ops.fused_distance import fused_argmin_min
from dask_ml_tpu.parallel.sharding import prepare_data, unpad_rows
from dask_ml_tpu.utils.validation import check_array, check_random_state

logger = logging.getLogger(__name__)


def _minibatch_update(batch, wb, centers, v):
    """One Sculley update from an assigned batch: per-center batch sums and
    weighted counts via the one-hot contraction (the M-step idiom), then
    ``c_j ← (1 − η_j)·c_j + η_j·mean_j`` with ``η_j = n_j / v_j`` — centers
    that caught nothing stay put. Assignment is the FUSED family's
    argmin (not a private distance matrix)."""
    k = centers.shape[0]
    labels, _ = fused_argmin_min(batch, centers)
    onehot = jax.nn.one_hot(labels, k, dtype=jnp.float32) * wb[:, None]
    sums = onehot.T @ batch.astype(jnp.float32)  # (k, d)
    counts = jnp.sum(onehot, axis=0)  # (k,)
    v_new = v + counts
    eta = jnp.where(counts > 0, counts / jnp.maximum(v_new, 1.0), 0.0)
    mean = sums / jnp.maximum(counts, 1e-30)[:, None]
    centers = jnp.where(counts[:, None] > 0,
                        (1.0 - eta)[:, None] * centers
                        + eta[:, None] * mean,
                        centers)
    return centers, v_new, labels


@partial(jax.jit, static_argnames=("n_steps", "batch_size", "n_valid"))
def _minibatch_steps(X, w, centers0, v0, key, *, n_steps: int,
                     batch_size: int, n_valid: int):
    """All mini-batch steps as one ``lax.scan``: step t draws
    ``batch_size`` row indices uniformly from the ``n_valid`` real rows
    (with replacement — the Sculley sampling model) and applies one
    update. ``n_steps`` is static (it sizes the scan's key array), so
    one program serves every fit at the same (shape, epochs) signature —
    the same compile-cache discipline as ``lloyd_loop``'s ``max_iter``.
    """
    def step(carry, kt):
        centers, v = carry
        idx = jax.random.randint(kt, (batch_size,), 0, n_valid)
        batch = jnp.take(X, idx, axis=0)
        wb = jnp.take(w, idx)
        centers, v, _ = _minibatch_update(batch, wb, centers, v)
        return (centers, v), None

    keys = jax.random.split(key, n_steps)
    (centers, v), _ = jax.lax.scan(step, (centers0, v0), keys)
    return centers, v


@jax.jit
def _partial_step(X, w, centers, v):
    return _minibatch_update(X, w, centers, v)


class MiniBatchKMeans(TransformerMixin, BaseEstimator):
    """Mini-batch KMeans (Sculley 2010) on the fused assignment kernel.

    Parameters
    ----------
    n_clusters : int, default 8
    init : {'k-means||', 'k-means++', 'random'} or ndarray, default 'k-means||'
        Initial centers — the same dispatch as :class:`KMeans`
        (``models.kmeans.k_init``). The smart default matters more here
        than for full Lloyd: the Sculley update never moves a center
        that catches no batch points, so a center stranded by a bad
        random draw stays lost (sklearn's MiniBatchKMeans defaults to
        k-means++ for the same reason).
    batch_size : int, default 1024
    max_iter : int, default 10
        Epochs: each epoch runs ``ceil(n / batch_size)`` uniformly-drawn
        batches (sampling with replacement, so an "epoch" is a work
        budget, not a partition).
    compute_labels : bool, default True
        Run one full assignment pass after fitting to populate
        ``labels_``/``inertia_`` (exactly :class:`KMeans`'s post-loop
        re-assignment contract).
    random_state : int, jax PRNG key, or None

    Attributes: ``cluster_centers_``, ``labels_``, ``inertia_``,
    ``n_iter_`` (total mini-batch steps), ``counts_`` (per-center absorbed
    weight — the streaming state; ``partial_fit`` continues from it).
    """

    def __init__(self, n_clusters: int = 8, init: str = "k-means||",
                 batch_size: int = 1024, max_iter: int = 10,
                 compute_labels: bool = True, random_state=None,
                 oversampling_factor: float = 2.0, init_max_iter=None):
        self.n_clusters = n_clusters
        self.init = init
        self.batch_size = batch_size
        self.max_iter = max_iter
        self.compute_labels = compute_labels
        self.random_state = random_state
        self.oversampling_factor = oversampling_factor
        self.init_max_iter = init_max_iter

    def _init_centers(self, data, key):
        return core.k_init(
            data.X, data.weights, data.n, self.n_clusters, key,
            init=self.init, oversampling_factor=self.oversampling_factor,
            max_iter=self.init_max_iter, mesh=data.mesh)

    def fit(self, X, y=None, sample_weight=None):
        if self.n_clusters < 1:
            raise ValueError("n_clusters must be >= 1")
        if self.batch_size < 1:
            raise ValueError("batch_size must be >= 1")
        X = check_array(X)
        data = prepare_data(X, sample_weight=sample_weight)
        if self.n_clusters > data.n:
            raise ValueError(
                f"n_clusters={self.n_clusters} must be <= "
                f"n_samples={data.n}")
        key = check_random_state(self.random_state)
        key, k_init_key, k_steps = jax.random.split(key, 3)
        centers = self._init_centers(data, k_init_key)
        bs = int(min(self.batch_size, data.n))
        steps_per_epoch = -(-data.n // bs)
        n_steps = int(max(self.max_iter, 1)) * steps_per_epoch
        centers, v = _minibatch_steps(
            data.X, data.weights, jnp.asarray(centers, jnp.float32),
            jnp.zeros((self.n_clusters,), jnp.float32), k_steps,
            n_steps=n_steps, batch_size=bs, n_valid=data.n)
        self.cluster_centers_ = np.asarray(centers)
        self.counts_ = np.asarray(v)
        self.n_iter_ = int(n_steps)
        self.n_features_in_ = data.n_features
        if self.compute_labels:
            labels = core.predict_labels(data.X, centers)
            self.labels_ = np.asarray(
                unpad_rows(labels, data.n)).astype(np.int32)
            self.inertia_ = float(
                core.compute_inertia(data.X, data.weights, centers))
        return self

    def partial_fit(self, X, y=None, sample_weight=None):
        """One mini-batch update from the given rows (the whole input is
        the batch). First call initializes centers from the batch."""
        X = check_array(X)
        data = prepare_data(X, sample_weight=sample_weight)
        if not hasattr(self, "cluster_centers_"):
            key = check_random_state(self.random_state)
            if self.n_clusters > data.n:
                raise ValueError(
                    f"n_clusters={self.n_clusters} must be <= "
                    f"n_samples={data.n} in the first partial_fit batch")
            self.cluster_centers_ = np.asarray(
                self._init_centers(data, key))
            self.counts_ = np.zeros((self.n_clusters,), np.float32)
            self.n_iter_ = 0
            self.n_features_in_ = data.n_features
        centers, v, _ = _partial_step(
            data.X, data.weights,
            jnp.asarray(self.cluster_centers_, jnp.float32),
            jnp.asarray(self.counts_))
        self.cluster_centers_ = np.asarray(centers)
        self.counts_ = np.asarray(v)
        self.n_iter_ += 1
        return self

    def _check_fitted(self):
        if not hasattr(self, "cluster_centers_"):
            raise AttributeError("Model not fitted; call fit first")

    def predict(self, X):
        self._check_fitted()
        X = check_array(X)
        data = prepare_data(X)
        labels = core.predict_labels(
            data.X, jnp.asarray(self.cluster_centers_))
        return maybe_host(unpad_rows(labels, data.n))

    def transform(self, X):
        from dask_ml_tpu.ops.pairwise import euclidean_distances

        self._check_fitted()
        X = check_array(X)
        data = prepare_data(X)
        d = euclidean_distances(data.X, jnp.asarray(self.cluster_centers_))
        return maybe_host(unpad_rows(d, data.n))

    def score(self, X, y=None):
        self._check_fitted()
        X = check_array(X)
        data = prepare_data(X)
        return -float(core.compute_inertia(
            data.X, data.weights, jnp.asarray(self.cluster_centers_)))


@_copy_partial_doc
class PartialMiniBatchKMeans(_BigPartialFitMixin, _MiniBatchKMeans):
    pass
