"""Nyström-approximate Spectral Clustering
(reference: cluster/spectral.py:23-356).

Algorithm (Fowlkes et al. 2004; Parallel Spectral Clustering in Distributed
Systems, Chen et al. 2010 — the references the reference cites at
spectral.py:127-137): sample ``n_components`` rows, compute the exact kernel
blocks A (l×l) and B (l×m), approximate the degree normalization, take the
SVD of the small normalized A, and map every remaining row through the
Nyström extension (Eq. 16) before clustering the embedding with KMeans.

TPU mapping: the big block is computed as ``Bt = kernel(X_rest, X_keep)``
— an (m, l) sharded-by-rows matmul against the replicated sample block — so
the N×N affinity never exists and all O(m) work is SPMD over the mesh; the
l×l eigensolve is replicated host-free compute. The reference's
``_slice_mostly_sorted`` re-ordering gather (spectral.py:319-356) becomes a
single host scatter of the (n, k) embedding.
"""

from __future__ import annotations

import logging
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from sklearn.base import BaseEstimator, ClusterMixin

from dask_ml_tpu.cluster.k_means import KMeans
from dask_ml_tpu.ops.pairwise import PAIRWISE_KERNEL_FUNCTIONS, pairwise_kernels
from dask_ml_tpu.parallel.sharding import replicate, shard_rows, unpad_rows
from dask_ml_tpu.parallel import telemetry
from dask_ml_tpu.utils._log import log_array
from dask_ml_tpu.utils.validation import check_array, check_random_state_np

logger = logging.getLogger(__name__)


def _check_affinity(metric):
    if isinstance(metric, str) and metric not in PAIRWISE_KERNEL_FUNCTIONS:
        raise ValueError(
            f"Unknown affinity metric name '{metric}'. Expected one of "
            f"{sorted(PAIRWISE_KERNEL_FUNCTIONS)}"
        )


class SpectralClustering(BaseEstimator, ClusterMixin):
    """Approximate spectral clustering via the Nyström method
    (reference: cluster/spectral.py:23-165 docstring; same constructor
    surface minus the dask-specific ``persist_embedding``)."""

    def __init__(self, n_clusters=8, eigen_solver=None, random_state=None,
                 n_init=10, gamma=1.0, affinity="rbf", n_neighbors=10,
                 eigen_tol=0.0, assign_labels="kmeans", degree=3, coef0=1,
                 kernel_params=None, n_jobs=1, n_components=100,
                 persist_embedding=False, kmeans_params=None):
        self.n_clusters = n_clusters
        self.eigen_solver = eigen_solver
        self.random_state = random_state
        self.n_init = n_init
        self.gamma = gamma
        self.affinity = affinity
        self.n_neighbors = n_neighbors
        self.eigen_tol = eigen_tol
        self.assign_labels = assign_labels
        self.degree = degree
        self.coef0 = coef0
        self.kernel_params = kernel_params
        self.n_jobs = n_jobs
        self.n_components = n_components
        self.persist_embedding = persist_embedding
        self.kmeans_params = kmeans_params

    def _make_km(self, rng):
        """Final-clustering estimator dispatch
        (reference: spectral.py:176-190)."""
        if isinstance(self.assign_labels, str):
            if self.assign_labels == "kmeans":
                km = KMeans(n_clusters=self.n_clusters,
                            random_state=rng.randint(2**31 - 1))
            elif self.assign_labels == "sklearn-kmeans":
                import sklearn.cluster

                km = sklearn.cluster.KMeans(n_clusters=self.n_clusters,
                                            random_state=rng)
            else:
                raise ValueError(
                    f"Unknown 'assign_labels' {self.assign_labels!r}"
                )
        elif isinstance(self.assign_labels, BaseEstimator):
            km = self.assign_labels
        else:
            raise TypeError(
                f"Invalid type {type(self.assign_labels)} for 'assign_labels'"
            )
        if self.kmeans_params:
            km.set_params(**self.kmeans_params)
        return km

    def fit(self, X, y=None):
        X = check_array(X)  # device array; NOT materialized on host
        n = int(X.shape[0])
        l = int(self.n_components)
        k = int(self.n_clusters)
        if n <= l:
            raise ValueError(
                "'n_components' must be smaller than the number of samples."
                f" Got {l} components and {n} samples"
            )
        # affinity-name validation (single authority, shared with embed())
        _check_affinity(self.affinity)
        rng = check_random_state_np(self.random_state)
        km = self._make_km(rng)

        params = dict(self.kernel_params or {})
        params["gamma"] = self.gamma
        params["degree"] = self.degree
        params["coef0"] = self.coef0

        # Stage X ONCE, row-sharded; every selection below is a device
        # gather (VERDICT r4 #6: the previous fit did np.asarray(X) +
        # host keep/rest indexing + re-staging — a full host round-trip
        # of the dataset on a slow link at the 1e6+-row scale this path
        # is built for).
        Xs, n_valid = shard_rows(X)
        log_array(logger, "spectral: staged X", Xs)

        # Row sample (reference: spectral.py:207-210) — indices drawn on
        # host (l ints), rows gathered on device inside the program.
        keep = rng.choice(np.arange(n), l, replace=False)
        keep.sort()

        # String metrics run the whole embedding as ONE jitted program
        # (the eager chain paid ~15 separate compiles). CALLABLE metrics
        # keep the eager path: users may close over numpy/sklearn code
        # that cannot trace (np.asarray on a tracer raises), and a fresh
        # callable per fit would leak a static jit-cache entry each time.
        params_t = tuple(sorted(params.items()))
        # plain span (no logger=): this phase never was a profile_phase
        # site, so it must not become a new DASK_ML_TPU_PROFILE_DIR
        # capture site
        with telemetry.span("spectral-nystrom",
                            landmarks=int(l), k=int(k)):
            if callable(self.affinity):
                V2, S_A, Xk, ext = _nystrom_eager(
                    Xs, jnp.asarray(keep), n_valid, float(n),
                    self.affinity, params, k)
            else:
                V2, S_A, Xk, ext = _nystrom_program(
                    Xs, jnp.asarray(keep),
                    jnp.asarray(n_valid, jnp.int32),
                    jnp.asarray(float(n), jnp.float32),
                    metric=self.affinity, params_t=params_t, k=k)
        U2 = unpad_rows(V2, n_valid)  # device, original row order

        # persist the Nyström extension state (landmarks + degree/eigenmap
        # factors, all small) so predict() can map NEW rows through the
        # same Eq. 16 extension and assign them to the fitted centers
        self._landmarks_ = np.asarray(Xk)
        self._extension_ = tuple(np.asarray(e) for e in ext)
        self._n_fit_rows_ = float(n)

        logger.info("k-means for assign_labels [starting]")
        if isinstance(km, KMeans):
            km.fit(U2)  # jax-native: embedding stays on device
        else:
            km.fit(np.asarray(U2))  # foreign estimator: one (n, k) fetch
        logger.info("k-means for assign_labels [finished]")

        self.assign_labels_ = km
        self.labels_ = np.asarray(km.labels_)
        self.eigenvalues_ = np.asarray(S_A[:k])
        return self

    def fit_predict(self, X, y=None):
        self.fit(X)
        return self.labels_

    def _kernel_params(self) -> dict:
        params = dict(self.kernel_params or {})
        params["gamma"] = self.gamma
        params["degree"] = self.degree
        params["coef0"] = self.coef0
        return params

    def _assign_staged(self, Xs):
        """Nearest-center labels for STAGED (padded, row-sharded) rows as
        the ONE jitted Nyström-extension + fused-assignment program —
        returns PADDED device labels; callers slice to the true row count
        host-side. Shared by :meth:`predict` and the serving loop's batch
        runners (:mod:`dask_ml_tpu.parallel.serving`), so served labels
        are structurally bit-identical to direct calls. Only valid for
        the jax-native configuration (string-kernel affinity + native
        KMeans assigner)."""
        km = self.assign_labels_
        if callable(self.affinity) or not isinstance(km, KMeans):
            raise ValueError(
                "staged assignment requires a string-kernel affinity and "
                "the native KMeans assigner")
        from dask_ml_tpu.parallel.mesh import default_mesh

        Xk = jnp.asarray(self._landmarks_)
        ainv_colsum, d1_si, map_k = (
            jnp.asarray(e) for e in self._extension_)
        scale = jnp.asarray(
            np.sqrt(int(self.n_components) / self._n_fit_rows_),
            jnp.float32)
        return _nystrom_assign_program(
            Xs, Xk, ainv_colsum, d1_si, map_k, scale,
            jnp.asarray(km.cluster_centers_),
            metric=self.affinity,
            params_t=tuple(sorted(self._kernel_params().items())),
            mesh=default_mesh())

    def predict(self, X):
        """Labels for NEW rows via the Nyström landmark-assignment path:
        kernel strip against the fitted landmarks, the same Eq. 16
        extension the fit used (:func:`_nystrom_extend` — training rows
        re-extend to their fit embedding exactly), then nearest-center
        assignment through the fused distance-reduction family
        (ops/fused_distance.py). The reference's SpectralClustering has no
        out-of-sample story at all; Nyström gives one for free."""
        if not hasattr(self, "assign_labels_"):
            raise AttributeError("Model not fitted; call fit first")
        X = check_array(X)
        from dask_ml_tpu.parallel import precision as precision_lib

        Xs, n_valid = shard_rows(
            X, dtype=precision_lib.staging_wire_dtype())
        km = self.assign_labels_
        if isinstance(km, KMeans) and not callable(self.affinity):
            # one program per shape bucket + host-side unpad: a repeat
            # predict in a warm bucket compiles nothing (docs/serving.md)
            return np.asarray(
                self._assign_staged(Xs))[:n_valid].astype(np.int32)
        # callable metrics run their kernel strip eagerly (same reasoning
        # as _nystrom_eager); foreign estimators assign on host
        params = self._kernel_params()
        ainv_colsum, d1_si, map_k = (
            jnp.asarray(e) for e in self._extension_)
        scale = jnp.asarray(
            np.sqrt(int(self.n_components) / self._n_fit_rows_),
            jnp.float32)
        Xk = jnp.asarray(self._landmarks_)
        if callable(self.affinity):
            C = jnp.asarray(self.affinity(Xs, replicate(Xk), **params))
        else:
            C = pairwise_kernels(Xs, Xk, metric=self.affinity, **params)
        V = _nystrom_extend_jit(C, ainv_colsum, d1_si, map_k, scale)
        V = unpad_rows(V, n_valid)
        if isinstance(km, KMeans):
            from dask_ml_tpu.models.kmeans import predict_labels

            return np.asarray(predict_labels(
                V, jnp.asarray(km.cluster_centers_))).astype(np.int32)
        return np.asarray(km.predict(np.asarray(V)))


@partial(jax.jit, static_argnames=("metric", "params_t", "k"))
def _nystrom_program(Xs, keep_idx, n_valid, n_true, *, metric, params_t,
                     k: int):
    """The ENTIRE Nyström embedding as one XLA program over the staged,
    row-sharded X: device gather of the sampled rows, both kernel blocks,
    unified degree normalization, the small replicated eigensolve, the
    Eq. 16 extension, and row normalization.

    Instead of the reference's disjoint keep/rest split (which would need
    an (n-l)-row gather — a second copy of X), the kernel strip
    C = K(X, X_keep) covers ALL rows, (n, l) sharded. The disjoint
    formulation falls out exactly: for keep rows the Nyström degree
    A·A⁻¹·C'1 equals C'1 (= a + b1), and for rest rows Bt·A⁻¹·a = Bt·1
    = b2 since a = A·1 — so the unified degree d = C·A⁻¹·(C'1)
    reproduces the reference's d1/d2 (spectral.py:225-246) and the
    embedding comes out already in ORIGINAL row order: the
    _slice_mostly_sorted re-ordering machinery (spectral.py:319-356)
    vanishes instead of becoming a host scatter.

    ``n_valid``/``n_true`` are traced scalars (padding mask and the l/n
    scale), so refits across sizes with one padded shape share the
    compile. ``metric`` (a kernel NAME — callables take
    :func:`_nystrom_eager` instead) and the kernel params are static.
    Returns ``(V2 (n_pad, k) sharded row-normalized embedding, S_A
    singular values, Xk landmarks, extension factors)``.
    """
    params = dict(params_t)
    Xk = jnp.take(Xs, keep_idx, axis=0)  # (l, d), replicated by GSPMD
    A = pairwise_kernels(Xk, Xk, metric=metric, **params)
    C = pairwise_kernels(Xs, Xk, metric=metric, **params)
    V2, S_A, ext = _nystrom_core(A, C, keep_idx, n_valid, n_true, k)
    return V2, S_A, Xk, ext


def _nystrom_map(C, ainv_colsum, d1_si, map_k, scale, *,
                 row_normalize: bool = True):
    """Map a kernel strip ``C = K(rows, landmarks)`` through fitted
    Nyström machinery: approximate degree, unified normalization, the
    eigenmap, optional row normalization. The ONE extension seam of the
    Nyström family — spectral clustering consumes it row-normalized
    (Eq. 4) with the top-k eigenmap, kernel k-means
    (cluster/kernel_kmeans.py) consumes it UN-normalized with the full
    l-column whitening map (its feature rows must keep their kernel
    geometry: ``Φ Φᵀ ≈ D^-½ K D^-½``, and row-normalizing would destroy
    the inner products the kernel-space centroids live in)."""
    d_row = C @ ainv_colsum  # approximate row degrees
    d_si = 1.0 / jnp.sqrt(jnp.maximum(d_row, 1e-12))
    C2 = d_si[:, None] * C * d1_si[None, :]
    V = scale * (C2 @ map_k)
    if not row_normalize:
        return V
    # Row-normalize (Eq. 4, reference: spectral.py:266).
    return V / jnp.maximum(jnp.linalg.norm(V, axis=1, keepdims=True), 1e-12)


def _nystrom_extend(C, ainv_colsum, d1_si, map_k, scale):
    """The spectral-clustering view of :func:`_nystrom_map` (always
    row-normalized) — ONE definition used for the training rows
    (:func:`_nystrom_core`) and for out-of-sample rows
    (:meth:`SpectralClustering.predict`) — training-row re-extension
    reproduces the fit embedding exactly."""
    return _nystrom_map(C, ainv_colsum, d1_si, map_k, scale,
                        row_normalize=True)


def _nystrom_core(A, C, keep_idx, n_valid, n_true, k: int):
    """The post-kernel Nyström math (degree normalization, eigensolve,
    Eq. 16, row normalization) — ONE definition shared by the fully-jitted
    string-metric program and the eager callable-metric path. Returns the
    embedding, the singular values, and the extension factors
    ``(ainv_colsum, d1_si, map_k)`` that :func:`_nystrom_extend` needs to
    map further rows into the same embedding."""
    row_valid = jnp.arange(C.shape[0]) < n_valid
    C = jnp.where(row_valid[:, None], C, 0.0)  # padding rows drop out

    colsum = C.sum(0)  # (l,) = a + b1: column degree over ALL rows
    A_inv = jnp.linalg.pinv(A)
    ainv_colsum = A_inv @ colsum  # (l,) degree functional
    d_all = C @ ainv_colsum  # (n_pad,) approximate row degrees
    d_si = 1.0 / jnp.sqrt(jnp.maximum(d_all, 1e-12))
    d1_si = jnp.take(d_si, keep_idx)  # keep rows' exact a+b1 degrees

    A2 = d1_si[:, None] * A * d1_si[None, :]

    # Small replicated eigensolve (reference: delayed scipy svd,
    # spectral.py:248-252).
    U_A, S_A, _ = jnp.linalg.svd(A2)

    # Nyström extension, Eq. 16 (reference: spectral.py:254-263),
    # applied uniformly (C2's keep rows ARE A2's rows).
    map_k = U_A[:, :k] * (1.0 / jnp.sqrt(S_A[:k]))[None, :]
    l_count = keep_idx.shape[0]
    scale = jnp.sqrt(l_count / n_true)
    V2 = _nystrom_extend(C, ainv_colsum, d1_si, map_k, scale)
    return V2, S_A, (ainv_colsum, d1_si, map_k)


_nystrom_core_jit = partial(jax.jit, static_argnames=("k",))(_nystrom_core)
_nystrom_extend_jit = jax.jit(_nystrom_extend)


def _nystrom_eager(Xs, keep_idx, n_valid: int, n_true: float, metric,
                   params: dict, k: int):
    """Callable-metric path: the kernel blocks run EAGERLY (the callable
    may use numpy/sklearn code that cannot trace, and making it a static
    jit arg would leak a compile-cache entry per callable instance); the
    block math still runs as one jitted, callable-independent program."""
    Xk = replicate(jnp.take(Xs, keep_idx, axis=0))
    A = jnp.asarray(metric(Xk, Xk, **params))
    C = jnp.asarray(metric(Xs, Xk, **params))
    V2, S_A, ext = _nystrom_core_jit(
        A, C, keep_idx, jnp.asarray(n_valid, jnp.int32),
        jnp.asarray(n_true, jnp.float32), k=k)
    return V2, S_A, Xk, ext


@partial(jax.jit, static_argnames=("metric", "params_t", "mesh"))
def _nystrom_assign_program(Xs, Xk, ainv_colsum, d1_si, map_k, scale,
                            centers, *, metric, params_t, mesh):
    """Out-of-sample Nyström landmark assignment as ONE jitted program:
    kernel strip against the fitted landmarks, the Eq. 16 extension, and
    the nearest-center assignment — the last step routed through the
    fused distance-reduction family (ops/fused_distance.py), so at the
    1e6+-row scale this path is built for no (n × k) distance matrix is
    materialized between the embedding and its labels."""
    from dask_ml_tpu.ops.fused_distance import fused_argmin_min

    C = pairwise_kernels(Xs, Xk, metric=metric, **dict(params_t))
    V = _nystrom_extend(C, ainv_colsum, d1_si, map_k, scale)
    labels, _ = fused_argmin_min(V, centers, mesh=mesh)
    return labels


def embed(X_keep, X_rest, n_components, metric, kernel_params):
    """Kernel blocks of the Nyström embedding
    (reference: spectral.py:293-316 ``embed``).

    Stages the sampled rows replicated and the rest row-sharded over the
    mesh, then computes ``A = K(X_keep, X_keep)`` (small, replicated) and
    ``Bt = K(X_rest, X_keep)`` — the TRANSPOSE of the reference's ``B``,
    laid out (m, l) so the big block shards along the sample axis and each
    device computes only its rows' kernel strip on the MXU. Padding rows of
    ``Bt`` are zeroed so column sums over the sharded axis stay exact.

    Callable metrics receive ``(X, Y, **kernel_params)`` — two operands,
    unlike the reference's one-or-two convention — matching this class's
    ``affinity`` contract.
    """
    _check_affinity(metric)
    if n_components != len(X_keep):
        raise ValueError(
            f"n_components={n_components} must equal the number of sampled "
            f"rows len(X_keep)={len(X_keep)}"
        )
    params = dict(kernel_params or {})
    Xk = replicate(np.asarray(X_keep))
    Xr, m_valid = shard_rows(np.asarray(X_rest))
    if callable(metric):
        A = metric(Xk, Xk, **params)
        Bt = metric(Xr, Xk, **params)
    else:
        A = pairwise_kernels(Xk, Xk, metric=metric, **params)
        Bt = pairwise_kernels(Xr, Xk, metric=metric, **params)
    wmask = (jnp.arange(Bt.shape[0]) < m_valid)[:, None]
    return A, jnp.where(wmask, Bt, 0.0)
