"""KMeans estimator over the jitted Lloyd/k-means|| core
(reference: cluster/k_means.py:26-216 ``KMeans``).

The sklearn-style shell keeps the reference's API (constructor signature,
trailing-underscore learned attributes) while the compute path is the pure
functional core in :mod:`dask_ml_tpu.models.kmeans`: one XLA program for the
whole Lloyd optimization, SPMD over the data-sharded mesh.
"""

from __future__ import annotations

import logging
from timeit import default_timer as tic

import jax
import jax.numpy as jnp
import numpy as np
from sklearn.base import BaseEstimator, TransformerMixin

from dask_ml_tpu.config import maybe_host
from dask_ml_tpu.models import kmeans as core
from dask_ml_tpu.ops.pairwise import euclidean_distances
from dask_ml_tpu.parallel import telemetry
from dask_ml_tpu.parallel.sharding import prepare_data, unpad_rows
from dask_ml_tpu.utils.validation import check_array, check_random_state

logger = logging.getLogger(__name__)

#: Sketched-epilogue dispatch for the QuicK-means restricted Lloyd
#: rounds: ``True`` (default) runs them through ``lloyd_loop_bounded``,
#: driving the fused family's ``row_need`` block-skip on the staged
#: sketch columns — exact by the BOUNDS theorem, so the sketched fit is
#: bit-identical to the fused-loop epilogue (pinned in
#: tests/test_asha.py; tests flip this to obtain the fused reference).
_SKETCHED_BOUNDED = True


class KMeans(TransformerMixin, BaseEstimator):
    """Scalable KMeans with k-means|| initialization.

    Parameters mirror the reference estimator
    (reference: cluster/k_means.py:26-141):

    n_clusters : int, default 8
    init : {'k-means||', 'k-means++', 'random'} or ndarray
        'k-means||' (default) is the parallel oversampling init of Bahmani
        et al.; 'k-means++' materializes data on the host and is only
        sensible for modest n (same caveat as the reference).
    oversampling_factor : float, default 2
        ℓ = oversampling_factor · n_clusters candidates drawn per init round.
    max_iter : int, default 300
    tol : float, default 1e-4 — scaled by mean feature variance, as in
        sklearn and the reference.
    random_state : int, jax PRNG key, or None
    init_max_iter : int or None — cap on k-means|| rounds.
    algorithm : {'full', 'lloyd', 'bounded', 'elkan', 'auto', 'sketched'},
        default 'full'.
        Lloyd-iteration implementation. 'full' (alias 'lloyd') is the
        plain fused loop; 'bounded' (alias 'elkan', sklearn's name for
        the idea) carries Elkan/Yinyang center-movement bounds and skips
        the distance pass block-wise for rows whose bounds prove the
        assignment unchanged — converged centers, labels, and inertia
        are bit-identical to 'full' (pinned by test), only the work
        differs; 'auto' picks 'bounded' in its winning regimes
        (``models.kmeans._bounded_auto_wins``). A bounded fit exposes
        its pruning counters as ``lloyd_pruning_``. 'sketched' is the
        APPROXIMATE QuicK-means path (arxiv 1908.08713): centers are
        constrained to a learned fast-transform sketch
        (ops/fast_transform.py) and the Lloyd loop runs in the
        ``sketch_cols``-column transform space — O(n·k·p) assignments
        instead of O(n·k·d), at a quality cost gated by bench.py
        ``--sketch`` (inertia-ratio and ARI vs exact; docs/kernels.md,
        "Sketched assignment"). A sketched fit additionally exposes
        ``fast_transform_``, ``sketch_support_``, ``sketch_vals_``, and
        ``sketch_loss_``.
    sketch_cols : int or None, default None ('sketched' only)
        Columns p of the shared sketch support; None picks
        ``max(4, n_features // 4)``.
    sketch_iters : int, default 8 ('sketched' only)
        palm4MSA alternations fitting the transform to the init centers.
    n_jobs / precompute_distances / copy_x are accepted for signature
        parity and ignored (placement is the mesh's job).

    Attributes
    ----------
    cluster_centers_ : (n_clusters, n_features) ndarray
    labels_ : (n_samples,) ndarray
    inertia_ : float
    n_iter_ : int
    """

    def __init__(
        self,
        n_clusters: int = 8,
        init: str = "k-means||",
        oversampling_factor: float = 2.0,
        max_iter: int = 300,
        tol: float = 1e-4,
        precompute_distances: str = "auto",
        random_state=None,
        copy_x: bool = True,
        n_jobs: int = 1,
        algorithm: str = "full",
        init_max_iter=None,
        sketch_cols=None,
        sketch_iters: int = 8,
    ):
        self.n_clusters = n_clusters
        self.init = init
        self.oversampling_factor = oversampling_factor
        self.max_iter = max_iter
        self.tol = tol
        self.precompute_distances = precompute_distances
        self.random_state = random_state
        self.copy_x = copy_x
        self.n_jobs = n_jobs
        self.algorithm = algorithm
        self.init_max_iter = init_max_iter
        self.sketch_cols = sketch_cols
        self.sketch_iters = sketch_iters

    def _check_params(self, n_samples=None):
        if self.n_clusters < 1:
            raise ValueError("n_clusters must be >= 1")
        if self.max_iter < 1:
            raise ValueError("max_iter must be >= 1")
        if n_samples is not None and self.n_clusters > n_samples:
            raise ValueError(
                f"n_clusters={self.n_clusters} must be <= n_samples={n_samples}"
            )
        if self.algorithm not in ("full", "lloyd", "bounded", "elkan",
                                  "auto", "sketched"):
            raise ValueError(
                "algorithm must be 'full'/'lloyd', 'bounded'/'elkan', "
                f"'auto', or 'sketched'; got {self.algorithm!r}")
        if self.sketch_cols is not None and int(self.sketch_cols) < 1:
            raise ValueError("sketch_cols must be >= 1")
        if int(self.sketch_iters) < 0:
            raise ValueError("sketch_iters must be >= 0")

    def _use_bounded(self, n: int, d: int) -> bool:
        if self.algorithm in ("bounded", "elkan"):
            return True
        if self.algorithm == "auto":
            return core._bounded_auto_wins(n, self.n_clusters, d)
        return False

    def fit(self, X, y=None, sample_weight=None):
        t0 = tic()
        X = check_array(X)
        self._check_params(n_samples=int(X.shape[0]))
        fit_span = telemetry.span(
            "kmeans.fit", n=int(X.shape[0]), d=int(X.shape[1]),
            k=int(self.n_clusters))
        with fit_span as fsp:
            return self._fit_instrumented(X, sample_weight, t0, fsp)

    def _fit_instrumented(self, X, sample_weight, t0, fit_sp):
        data = prepare_data(X, sample_weight=sample_weight)
        key = check_random_state(self.random_state)

        with telemetry.span(
                "kmeans.init",
                init=self.init if isinstance(self.init, str) else "array"):
            centers = core.k_init(
                data.X,
                data.weights,
                data.n,
                self.n_clusters,
                key,
                init=self.init,
                oversampling_factor=self.oversampling_factor,
                max_iter=self.init_max_iter,
                mesh=data.mesh,
            )
        t_init = tic()
        logger.info("init (%s) finished in %.2fs", self.init, t_init - t0)

        if self.algorithm == "sketched":
            return self._finish_sketched(data, centers, t0, t_init)

        tol = core.scaled_tolerance(data.X, data.weights, self.tol)
        bounded = self._use_bounded(data.n, data.n_features)
        with telemetry.span("kmeans-lloyd", logger=logger,
                            algorithm="bounded" if bounded else "lloyd"):
            if bounded:
                from dask_ml_tpu.parallel.precision import lloyd_bounds_dtype

                centers, _, n_iter, _, _, prune_stats = \
                    core.lloyd_loop_bounded(
                        data.X, data.weights, centers, tol,
                        mesh=data.mesh, max_iter=self.max_iter,
                        bounds_dtype=lloyd_bounds_dtype(data.X.dtype),
                    )
            else:
                centers, _, n_iter, _ = core.lloyd_loop_fused(
                    data.X, data.weights, centers, tol,
                    mesh=data.mesh, max_iter=self.max_iter,
                )
        # Recompute cost against the *final* centers so inertia_ is consistent
        # with cluster_centers_/labels_ and score(X) — the reference likewise
        # re-assigns after the loop (reference: cluster/k_means.py:504-507).
        with telemetry.span("kmeans.finalize"):
            inertia = core.compute_inertia(data.X, data.weights, centers)
            labels = core.predict_labels(data.X, centers)
        t_lloyd_done = tic()
        logger.info(
            "Lloyd finished in %.2fs: %d iterations, inertia %.4g",
            t_lloyd_done - t_init, int(n_iter), float(inertia),
        )
        if telemetry.enabled():
            # the whole Lloyd loop is ONE compiled while_loop — individual
            # iteration walls are not host-observable, so the registry gets
            # the iteration count plus the mean seconds/iteration per fit
            # (a distribution ACROSS fits), and — for bounded runs below —
            # the true per-iteration pruned-fraction histogram the loop's
            # carried counters do expose
            reg = telemetry.metrics()
            reg.histogram("kmeans.lloyd.iterations").observe(int(n_iter))
            reg.histogram("kmeans.lloyd.seconds_per_iter").observe(
                (t_lloyd_done - t_init) / max(int(n_iter), 1))

        self.cluster_centers_ = np.asarray(centers)
        # labels cross the (slow) host link once per fit; with k <= 255
        # they travel as uint8 — 4x less traffic than int32, same values
        # (int32 restored host-side for the sklearn-shaped attribute)
        if self.n_clusters <= 255:
            labels = labels.astype(jnp.uint8)
        self.labels_ = np.asarray(unpad_rows(labels, data.n)).astype(np.int32)
        self.inertia_ = float(inertia)
        self.n_iter_ = int(n_iter)
        self.n_features_in_ = data.n_features
        if bounded:
            # pruning observability (surfaced next to the PR-2 roofline
            # keys by bench_kdd as lloyd_pruned_fraction): rows_skipped
            # counts distance work actually avoided (block granularity),
            # bounds_held the rows whose bound held (row granularity)
            n_it = int(n_iter)
            skip = np.asarray(
                jax.device_get(prune_stats["rows_skipped"]))[:n_it]
            held = np.asarray(
                jax.device_get(prune_stats["bounds_held"]))[:n_it]
            # the loop's counters run over POSITIVE-weight rows only, so
            # the fractions must too — under zero sample_weights (or row
            # padding) data.n would understate the pruning rate
            n_real = int(jax.device_get(
                jnp.sum((data.weights > 0).astype(jnp.int32))))
            denom = max(n_real, 1)
            self.lloyd_pruning_ = {
                "rows_skipped": int(skip.sum()),
                "rows_considered": n_it * n_real,
                "distances_avoided": int(skip.sum()) * int(self.n_clusters),
                "pruned_fraction_per_iter": [
                    float(s) / denom for s in skip],
                "bound_held_fraction_per_iter": [
                    float(h) / denom for h in held],
            }
            if telemetry.enabled():
                # registry mirrors of lloyd_pruning_, same values (pinned
                # by tests/test_telemetry.py); the per-ITERATION pruned
                # fractions feed the histogram
                reg = telemetry.metrics()
                reg.counter("kmeans.lloyd.rows_skipped").inc(
                    self.lloyd_pruning_["rows_skipped"])
                reg.counter("kmeans.lloyd.rows_considered").inc(
                    self.lloyd_pruning_["rows_considered"])
                reg.counter("kmeans.lloyd.distances_avoided").inc(
                    self.lloyd_pruning_["distances_avoided"])
                h = reg.histogram("kmeans.lloyd.pruned_fraction")
                for frac in self.lloyd_pruning_["pruned_fraction_per_iter"]:
                    h.observe(frac)
                fit_sp.set(lloyd_pruned_fraction=round(
                    self.lloyd_pruning_["rows_skipped"]
                    / max(self.lloyd_pruning_["rows_considered"], 1), 4))
        # phase split for benchmarks/observability: init ends at the
        # device_get barrier inside k_init; lloyd covers the fused loop +
        # final re-assignment fetch
        self.fit_phase_seconds_ = {
            "init": t_init - t0, "lloyd": tic() - t_init}
        return self

    def _finish_sketched(self, data, centers, t0, t_init):
        """The QuicK-means fit: palm4MSA-fit a fast transform + shared
        support to the init centers, transform the data ONCE (amortized
        over every Lloyd iteration), and run the STANDARD fused Lloyd
        loop on the support-restricted columns. The restricted loop IS
        the constrained optimization: for an orthogonal transform with a
        fixed support, the full-space M-step followed by re-projection
        onto the transform product equals the plain M-step on the
        restricted data (mean of restrictions == restriction of the
        mean), and restricted distances differ from full-space distances
        to the sketched centers by a per-row constant — identical
        argmins. So the sketched path inherits the fused loop whole:
        hierarchy-metered ``kmeans.mstep`` collectives, compile-once
        buckets, kernel auto-dispatch.

        Two QuicK-means alternation rounds: the first transform is fit
        on the INIT centers, which are the wrong geometry once Lloyd has
        moved — so after the loop converges, refit transform + support
        on the reconstructed converged centers and run a second (short —
        it starts converged) restricted loop. Finalization is honest
        data-space accounting: ``labels_`` come from the sketched
        assignment the served model will actually run, and
        ``cluster_centers_``/``inertia_`` are the EXACT weighted means
        of that partition and its exact within-partition SSE (one
        O(n·k·d) polish pass — for a fixed partition the exact means are
        optimal, so the sketch approximation is confined to where it
        belongs, the partition itself, and the inertia-ratio bench gate
        measures partition quality, not reconstruction roundoff)."""
        from dask_ml_tpu.ops import fast_transform as ftm

        d = data.n_features
        p = (int(self.sketch_cols) if self.sketch_cols is not None
             else max(4, d // 4))
        with telemetry.span("kmeans.sketch-fit", p=p,
                            iters=int(self.sketch_iters)):
            # Center on the weighted data mean before sketching: k-means
            # geometry is translation-invariant, and a shared mean
            # component would waste support budget on a direction that
            # cancels in every distance comparison.
            w32 = data.weights.astype(jnp.float32)
            mu = (w32 @ data.X.astype(jnp.float32)
                  ) / jnp.maximum(jnp.sum(w32), 1e-12)
            ft, support, vals0, fit_loss = ftm.palm4msa_fit(
                centers - mu[None, :].astype(centers.dtype), p,
                n_iter=int(self.sketch_iters))
            Zp = _sketch_stage(ft, data.X, mu, support)
        def _restricted_lloyd(Zp_, vals0_, tol_):
            # One restricted Lloyd round. Default dispatch is the BOUNDED
            # loop: the sketch staging Zp is plain (n, p) data to the
            # family, so the Elkan/Yinyang bounds drive the ``row_need``
            # block-skip through the sketched epilogue's distance passes —
            # and by the BOUNDS theorem the trajectory is bit-identical
            # to the fused loop (pruning removes work, never changes
            # bytes; pinned in tests/test_asha.py). Returns
            # (vals, n_iter, prune_stats-or-None).
            if _SKETCHED_BOUNDED:
                from dask_ml_tpu.parallel.precision import \
                    lloyd_bounds_dtype

                vals_, _, n_it, _, _, stats = core.lloyd_loop_bounded(
                    Zp_, data.weights, vals0_, tol_, mesh=data.mesh,
                    max_iter=self.max_iter,
                    bounds_dtype=lloyd_bounds_dtype(Zp_.dtype))
                return vals_, int(n_it), stats
            vals_, _, n_it, _ = core.lloyd_loop_fused(
                Zp_, data.weights, vals0_, tol_,
                mesh=data.mesh, max_iter=self.max_iter)
            return vals_, int(n_it), None

        with telemetry.span("kmeans-lloyd", logger=logger,
                            algorithm="sketched"):
            tol = core.scaled_tolerance(Zp, data.weights, self.tol)
            vals, n_iter1, prune1 = _restricted_lloyd(Zp, vals0, tol)
            # round 2: refit on the converged (centered) reconstruction,
            # re-stage, continue the loop in the refreshed support
            with telemetry.span("kmeans.sketch-refit", p=p):
                ft, support, vals0, fit_loss = ftm.palm4msa_fit(
                    ftm.reconstruct(ft, vals, support), p,
                    n_iter=int(self.sketch_iters))
                Zp = _sketch_stage(ft, data.X, mu, support)
            tol = core.scaled_tolerance(Zp, data.weights, self.tol)
            vals, n_iter2, prune2 = _restricted_lloyd(Zp, vals0, tol)
            n_iter = int(n_iter1) + int(n_iter2)
        with telemetry.span("kmeans.finalize"):
            centers_sk = ftm.reconstruct(ft, vals, support) + mu[None, :]
            # materialize the (d, p) staging slice ONCE: every predict
            # (and the serving runner) is then one affine matmul, with
            # no per-call factor-ladder replay (support_matrix docstring)
            Wp = _support_matrix_j(ft, support)
            off = mu @ Wp
            labels = core.predict_labels_sketched(
                data.X, Wp, off, vals, centers_sk)
            centers_dense = _polish_centers(
                data.X, data.weights, labels, centers_sk)
            inertia = _assigned_inertia(
                data.X, data.weights, labels, centers_dense)
        t_done = tic()
        logger.info(
            "sketched Lloyd finished in %.2fs: %d iterations (p=%d), "
            "inertia %.4g", t_done - t_init, int(n_iter), p,
            float(inertia))
        if telemetry.enabled():
            reg = telemetry.metrics()
            reg.histogram("kmeans.lloyd.iterations").observe(int(n_iter))
            reg.histogram("kmeans.lloyd.seconds_per_iter").observe(
                (t_done - t_init) / max(int(n_iter), 1))
        self.cluster_centers_ = np.asarray(centers_dense)
        self.fast_transform_ = ftm.FastTransform(
            np.asarray(ft.angles), ft.d, ft.d_pad)
        self.sketch_mean_ = np.asarray(mu)
        self.sketch_centers_ = np.asarray(centers_sk)
        self.sketch_support_ = np.asarray(support)
        self.sketch_vals_ = np.asarray(vals)
        self.sketch_staging_ = np.asarray(Wp)
        self.sketch_offset_ = np.asarray(off)
        self.sketch_loss_ = float(fit_loss)
        if prune1 is not None:
            # pruning observability for the restricted rounds, the shape
            # of the exact path's ``lloyd_pruning_`` summed over both
            # QuicK-means rounds (and the same registry mirrors, at the
            # same increment site)
            skip = np.concatenate([
                np.asarray(jax.device_get(st["rows_skipped"]))[:ni]
                for st, ni in ((prune1, n_iter1), (prune2, n_iter2))])
            held = np.concatenate([
                np.asarray(jax.device_get(st["bounds_held"]))[:ni]
                for st, ni in ((prune1, n_iter1), (prune2, n_iter2))])
            n_real = int(jax.device_get(
                jnp.sum((data.weights > 0).astype(jnp.int32))))
            denom = max(n_real, 1)
            self.sketch_pruning_ = {
                "rows_skipped": int(skip.sum()),
                "rows_considered": int(n_iter) * n_real,
                "distances_avoided": int(skip.sum()) * int(self.n_clusters),
                "pruned_fraction_per_iter": [
                    float(s) / denom for s in skip],
                "bound_held_fraction_per_iter": [
                    float(h) / denom for h in held],
            }
            if telemetry.enabled():
                reg = telemetry.metrics()
                reg.counter("kmeans.lloyd.rows_skipped").inc(
                    self.sketch_pruning_["rows_skipped"])
                reg.counter("kmeans.lloyd.rows_considered").inc(
                    self.sketch_pruning_["rows_considered"])
                reg.counter("kmeans.lloyd.distances_avoided").inc(
                    self.sketch_pruning_["distances_avoided"])
                h = reg.histogram("kmeans.lloyd.pruned_fraction")
                for frac in self.sketch_pruning_[
                        "pruned_fraction_per_iter"]:
                    h.observe(frac)
        if self.n_clusters <= 255:
            labels = labels.astype(jnp.uint8)
        self.labels_ = np.asarray(
            unpad_rows(labels, data.n)).astype(np.int32)
        self.inertia_ = float(inertia)
        self.n_iter_ = int(n_iter)
        self.n_features_in_ = data.n_features
        self.fit_phase_seconds_ = {
            "init": t_init - t0, "lloyd": tic() - t_init}
        return self

    def _sketch_args(self):
        """Device-side (Wp, off, vals, centers) of a sketched fit — the
        argument pack of ``models.kmeans.predict_labels_sketched``,
        shared by :meth:`predict` and the serving runner
        (parallel/serving.py) so both call the SAME jitted program.
        ``Wp``/``off`` are the fit-time-materialized staging slice and
        its centering offset (one affine matmul per predict, no ladder
        replay). The dense-centers slot is ``sketch_centers_`` (the
        reconstruction ``G·Wᵀ + μ``), NOT the polished
        ``cluster_centers_``: the facade's exact-dispatch branch must
        assign against the centers the sketch actually encodes, so both
        branches produce identical labels and the dispatch stays a pure
        perf decision."""
        return (jnp.asarray(self.sketch_staging_),
                jnp.asarray(self.sketch_offset_),
                jnp.asarray(self.sketch_vals_),
                jnp.asarray(self.sketch_centers_))

    def _check_fitted(self):
        if not hasattr(self, "cluster_centers_"):
            raise AttributeError("Model not fitted; call fit first")

    def predict(self, X):
        """Nearest-center labels (reference: cluster/k_means.py:196-216).
        Host-path transfers travel as uint8 when k <= 255 (4x less
        host-link traffic; int32 restored host-side). The host path
        slices padding off AFTER the fetch, so a repeat predict whose n
        lands in a warm shape bucket compiles nothing (the serving-path
        contract, docs/serving.md)."""
        self._check_fitted()
        X = check_array(X)
        data = prepare_data(X)
        if getattr(self, "fast_transform_", None) is not None:
            labels = core.predict_labels_sketched(
                data.X, *self._sketch_args())
        else:
            labels = core.predict_labels(
                data.X, jnp.asarray(self.cluster_centers_))
        from dask_ml_tpu.config import get_config

        if not get_config()["device_outputs"]:
            if self.n_clusters <= 255:
                return np.asarray(
                    labels.astype(jnp.uint8))[:data.n].astype(np.int32)
            return np.asarray(labels)[:data.n]
        return maybe_host(unpad_rows(labels, data.n))

    def transform(self, X):
        """Distances to each center (reference: cluster/k_means.py:191-194)."""
        self._check_fitted()
        X = check_array(X)
        data = prepare_data(X)
        d = euclidean_distances(data.X, jnp.asarray(self.cluster_centers_))
        return maybe_host(unpad_rows(d, data.n))

    def score(self, X, y=None):
        """Negative inertia on X (higher is better), matching sklearn."""
        self._check_fitted()
        X = check_array(X)
        data = prepare_data(X)
        return -float(
            core.compute_inertia(
                data.X, data.weights, jnp.asarray(self.cluster_centers_)
            )
        )

    # -- batched-candidate protocol (search driver fast path) -------------
    #
    # The search driver buckets homogeneous candidates (same estimator
    # class, same static params, same upstream data) and fits+scores the
    # whole bucket as ONE compiled program (SURVEY §2.9 task-parallelism
    # row; VERDICT r3 #1). KMeans supports batching over (n_clusters, tol):
    # tol variants share one Lloyd trajectory, k variants share one masked
    # program — see models/kmeans.py batched_lloyd_cells.

    _batchable_params = frozenset({"n_clusters", "tol"})

    def _supports_batched(self, static_params) -> bool:
        """Batchable only with on-device ``init='random'`` — the k-means||
        and k-means++ inits are host-driven loops that would serialize the
        group (and per-candidate inits would defeat trajectory sharing)."""
        return static_params.get("init", self.init) == "random"

    def _batchable_member_ok(self, member_params, n_train_min) -> bool:
        """A member whose n_clusters can't fit the smallest train split
        must run per-cell so ITS failure follows error_score semantics
        instead of failing the whole group program."""
        k = int(member_params.get("n_clusters", self.n_clusters))
        return k >= 1 and (n_train_min is None or k <= n_train_min)

    def _batched_fit_score(self, X, y, members, eval_sets):
        """Fit every member (dict of batchable-param overrides) and score
        (negative inertia) each against each eval set — ``eval_sets`` is a
        list of ``(X_eval, y_eval)`` pairs (y unused by KMeans; supervised
        implementers of the protocol score against it). Returns
        ``{"n_iter": (M,), "scores": [per eval set (M,) arrays]}`` where the
        arrays are DEVICE arrays — the call is pure async dispatch; the
        search driver bulk-fetches all groups' outputs in one sync.

        TRUSTED device-array inputs (CV slices scanned at upload, chain
        intermediates from validated input — see ``StagingMemo.trust``)
        skip the NaN-scan sync inside ``check_array``; untrusted input is
        validated as anywhere else.

        Returns ``NotImplemented`` when the trajectory history the program
        would materialize (unique_ks × max_iter × max_k × d) exceeds a
        sane HBM budget — e.g. the estimator's default ``max_iter=300``
        with wide data — and the driver then runs the group per-cell,
        whose ``while_loop`` stops at convergence without storing
        history."""
        ks = {int(m.get("n_clusters", self.n_clusters)) for m in members}
        hist_bytes = (len(ks) * int(self.max_iter) * max(ks)
                      * int(X.shape[1]) * 4)
        # decline BEFORE validating/staging anything (the whole point is to
        # bail out): on memory (history buffer) or scan length — the
        # batched program runs a fixed-length scan of max_iter steps
        # (frozen steps are cheap but not free), while the per-cell
        # while_loop stops at convergence, so an extreme max_iter is
        # better served per-cell
        if hist_bytes > 512 * 1024 * 1024 or int(self.max_iter) > 4096:
            return NotImplemented
        data = prepare_data(check_array(X))
        evals = [prepare_data(check_array(E)) for E, _y in eval_sets]
        key = check_random_state(self.random_state)
        pairs = [
            (int(m.get("n_clusters", self.n_clusters)),
             float(m.get("tol", self.tol)))
            for m in members
        ]
        for k, _ in pairs:
            if k < 1 or k > data.n:
                raise ValueError(
                    f"n_clusters={k} must be in [1, n_samples={data.n}]")
        n_iters, _train_inertia, eval_inertias = core.batched_lloyd_cells(
            data, pairs, evals, max_iter=self.max_iter, key=key)
        return {
            "n_iter": n_iters,
            "scores": [-inert for inert in eval_inertias],
        }


def k_means(X, n_clusters, init="k-means||", precompute_distances="auto",
            n_init=1, max_iter=300, verbose=False, tol=1e-4,
            random_state=None, copy_x=True, n_jobs=-1, algorithm="full",
            return_n_iter=False, oversampling_factor=2, init_max_iter=None):
    """Functional K-means (reference: cluster/k_means.py:219-240).

    Thin wrapper over :class:`KMeans` — like the reference, ``n_init`` is
    effectively 1 (k-means|| makes restarts unnecessary) and the extra
    sklearn knobs are accepted for signature parity.
    Returns ``(centroids, labels, inertia[, n_iter])``.
    """
    est = KMeans(
        n_clusters=n_clusters, init=init,
        oversampling_factor=oversampling_factor, max_iter=max_iter, tol=tol,
        precompute_distances=precompute_distances, random_state=random_state,
        copy_x=copy_x, n_jobs=n_jobs, algorithm=algorithm,
        init_max_iter=init_max_iter,
    ).fit(X)
    if return_n_iter:
        return est.cluster_centers_, est.labels_, est.inertia_, est.n_iter_
    return est.cluster_centers_, est.labels_, est.inertia_


@jax.jit
def _assigned_inertia(Xs, w, labels_padded, centers):
    assigned = centers[labels_padded]
    return jnp.sum(w * jnp.sum((Xs - assigned) ** 2, axis=1))


@jax.jit
def _polish_centers(Xs, w, labels_padded, fallback_centers):
    """Exact data-space M-step for a FIXED partition: the weighted mean
    of every cluster's rows (one-hot matmul, so the sample-axis
    contraction stays a GSPMD-reducible dot like the fused M-step, not a
    serializing scatter-add). Empty clusters keep their fallback center.
    Used by the sketched finalize: for a given partition the exact means
    are SSE-optimal, so polishing confines the sketch approximation to
    the partition itself."""
    k = fallback_centers.shape[0]
    oh = (jax.nn.one_hot(labels_padded, k, dtype=jnp.float32)
          * w.astype(jnp.float32)[:, None])  # (n, k)
    cnt = jnp.sum(oh, axis=0)  # (k,)
    sums = jax.lax.dot_general(
        oh, Xs.astype(jnp.float32), (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)  # (k, d)
    means = sums / jnp.maximum(cnt, 1e-12)[:, None]
    return jnp.where((cnt > 0.0)[:, None], means, fallback_centers)


@jax.jit
def _sketch_stage(ft, Xs, mu, support):
    """Center + transform + support restriction of the staged data as
    ONE program: ``Z_p = (X - mu) @ Wᵀ[:, support]`` (n, p), the array
    the sketched Lloyd loop runs on. The thin transform slice is
    materialized once (ops/fast_transform.py ``support_matrix`` — see
    its docstring for why the slice-matmul, not the factor ladder, is
    the production staging path) so staging is one O(n·d·p) matmul.
    Row-wise, so GSPMD keeps it sharded with X."""
    from dask_ml_tpu.ops.fast_transform import support_matrix

    Wp = support_matrix(ft, support)
    return (Xs - mu.astype(Xs.dtype)[None, :]) @ Wp.astype(Xs.dtype)


@jax.jit
def _support_matrix_j(ft, support):
    """Jitted ``support_matrix``: the fit runs it once per sketched
    model to materialize the (d, p) staging slice predict/serving reuse
    — under jit the 8·sweeps sequential rotate levels fuse into one
    program instead of that many eager dispatches."""
    from dask_ml_tpu.ops.fast_transform import support_matrix

    return support_matrix(ft, support)


def compute_inertia(X, labels, centers):
    """Sum of squared distances of rows to their ASSIGNED center
    (reference: cluster/k_means.py:243-247) — one jitted gather + fused
    reduce over the sharded rows. Deliberate deviation, documented: the
    reference's code sums RAW differences (``(X - reindexed).sum()``, no
    square — a bug that can go negative); inertia here is the standard
    squared quantity, matching sklearn and this class's ``inertia_``."""
    data = prepare_data(X)
    labels = jnp.asarray(np.asarray(labels))
    centers = jnp.asarray(np.asarray(centers))
    pad = data.n_padded - data.n
    if pad:
        labels = jnp.concatenate([labels, jnp.zeros((pad,), labels.dtype)])
    return float(_assigned_inertia(data.X, data.weights, labels, centers))


def evaluate_cost(X, centers):
    """Σ min-squared-distance of each row to its nearest center — the
    k-means|| sampling cost (reference: cluster/k_means.py:425-428)."""
    data = prepare_data(X)
    return float(core.compute_inertia(
        data.X, data.weights, jnp.asarray(np.asarray(centers))))


def _staged_for_init(X, random_state):
    from dask_ml_tpu.utils.validation import check_random_state

    data = prepare_data(check_array(X))
    return data, check_random_state(random_state)


def k_init(X, n_clusters, init="k-means||", random_state=None, max_iter=None,
           oversampling_factor=2):
    """Choose initial centers — reference-signature facade
    (reference: cluster/k_means.py:254-325) over the functional core
    (``models.kmeans.k_init``, which works on pre-staged weighted shards).
    Returns a host ``(n_clusters, n_features)`` array."""
    data, key = _staged_for_init(X, random_state)
    return np.asarray(core.k_init(
        data.X, data.weights, data.n, int(n_clusters), key, init=init,
        oversampling_factor=oversampling_factor, max_iter=max_iter,
        mesh=data.mesh))


def init_scalable(X, n_clusters, random_state=None, max_iter=None,
                  oversampling_factor=2):
    """k-means|| init (reference: cluster/k_means.py:357-422)."""
    data, key = _staged_for_init(X, random_state)
    return np.asarray(core.init_scalable(
        data.X, data.weights, data.n, int(n_clusters), key,
        oversampling_factor=oversampling_factor, max_iter=max_iter,
        mesh=data.mesh))


def init_random(X, n_clusters, random_state=None):
    """Random-row init (reference: cluster/k_means.py:344-354)."""
    data, key = _staged_for_init(X, random_state)
    return np.asarray(core.init_random(
        data.X, data.weights, data.n, int(n_clusters), key))


def init_pp(X, n_clusters, random_state=None):
    """k-means++ init on gathered data — only sensible for modest n, the
    reference carries the same caveat (cluster/k_means.py:328-341)."""
    data, key = _staged_for_init(X, random_state)
    return np.asarray(core.init_pp(data.X, data.n, int(n_clusters), key))
