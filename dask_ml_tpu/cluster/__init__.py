"""Clustering estimators (reference: dask_ml/cluster/__init__.py)."""

from dask_ml_tpu.cluster.k_means import KMeans  # noqa: F401
