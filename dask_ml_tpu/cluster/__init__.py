"""Clustering estimators (reference: dask_ml/cluster/__init__.py)."""

from dask_ml_tpu.cluster.kernel_kmeans import KernelKMeans  # noqa: F401
from dask_ml_tpu.cluster.k_means import (  # noqa: F401
    KMeans,
    compute_inertia,
    evaluate_cost,
    init_pp,
    init_random,
    init_scalable,
    k_init,
    k_means,
)
from dask_ml_tpu.cluster.minibatch import (  # noqa: F401
    MiniBatchKMeans,
    PartialMiniBatchKMeans,
)
from dask_ml_tpu.cluster.spectral import SpectralClustering, embed  # noqa: F401

__all__ = ["KMeans", "KernelKMeans", "MiniBatchKMeans",
           "SpectralClustering", "PartialMiniBatchKMeans",
           "k_means", "compute_inertia", "evaluate_cost", "embed",
           "k_init", "init_pp", "init_random", "init_scalable"]
