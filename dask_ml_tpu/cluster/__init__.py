"""Clustering estimators (reference: dask_ml/cluster/__init__.py)."""

from dask_ml_tpu.cluster.k_means import KMeans  # noqa: F401
from dask_ml_tpu.cluster.minibatch import PartialMiniBatchKMeans  # noqa: F401
from dask_ml_tpu.cluster.spectral import SpectralClustering  # noqa: F401

__all__ = ["KMeans", "SpectralClustering", "PartialMiniBatchKMeans"]
