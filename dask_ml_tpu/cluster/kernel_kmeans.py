"""Nyström-approximate kernel k-means.

Dense Lloyd partitions by Euclidean proximity to mean vectors, so it can
only ever carve the space into convex (Voronoi) cells — a nonlinear
class boundary (concentric rings, interleaved arcs) is structurally out
of reach no matter how many restarts it gets. Kernel k-means (Dhillon,
Guan & Kulis 2004) lifts the rows through a kernel feature map and runs
the SAME Lloyd objective on inner products, which makes it equivalent to
a weighted graph cut — but the exact algorithm needs the full n×n Gram
matrix, per iteration. The scalable middle road implemented here
(following the landmark treatment of arxiv 2601.17136 and the Nyström
seam this repo already trusts for spectral clustering): sample l ≪ n
landmark rows, build the thin kernel strip ``C = K(X, X_l)`` (n, l)
sharded over the sample axis, and factor the degree-normalized Nyström
approximant ``K̂ = D^-½ C A⁺ Cᵀ D^-½ = Φ Φᵀ`` through the EXPLICIT
l-dimensional feature rows ``Φ = D^-½ C D_l^-½ · U S^-½``. Euclidean
k-means on Φ IS kernel k-means on K̂ (the lift makes the kernel-space
centroid distances literal vector distances), so the whole fused Lloyd
stack — assignment kernels, compile-once buckets, hierarchy-metered
M-step collectives — is inherited unchanged by handing Φ to the inner
:class:`~dask_ml_tpu.cluster.k_means.KMeans`.

The shared seam with spectral clustering is
:func:`~dask_ml_tpu.cluster.spectral._nystrom_map`: spectral consumes it
row-normalized with the top-k eigenmap (Eq. 4 of Ng-Jordan-Weiss),
kernel k-means consumes it UN-normalized with the FULL l-column
whitening map — row-normalizing would destroy the inner products the
kernel-space centroids live in, and truncating to k columns would make
this spectral clustering by another name. Small eigenvalues are
THRESHOLDED, not inverted (``1/√S`` only where ``S > S₀·1e-6``, zero
otherwise): A's trailing spectrum is noise the pseudo-inverse would
amplify into the features.

The fit's one sample-axis collective is the Gram-strip column degree
``Σ_rows C`` (every other reduction lives inside the inner KMeans, which
meters its own M-step). It routes through
:func:`~dask_ml_tpu.parallel.hierarchy.hpsum` on hierarchical meshes
(ledger op ``kernel.gram.colsum`` — chip-then-pod staged accounting) and
is recorded flat otherwise, the ``fused.argmin_weight`` convention.

Out-of-sample ``predict`` mirrors the spectral landmark-assignment path:
one jitted program (kernel strip → un-normalized extension → fused
nearest-center assignment), shared with the serving runners so served
predictions are bit-identical to direct calls.
"""

from __future__ import annotations

import logging
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from sklearn.base import BaseEstimator, ClusterMixin

from dask_ml_tpu.cluster.k_means import KMeans
from dask_ml_tpu.cluster.spectral import _check_affinity, _nystrom_map
from dask_ml_tpu.ops.pairwise import pairwise_kernels
from dask_ml_tpu.parallel import telemetry
from dask_ml_tpu.parallel.sharding import shard_rows, unpad_rows
from dask_ml_tpu.utils._log import log_array
from dask_ml_tpu.utils.validation import check_array, check_random_state_np

logger = logging.getLogger(__name__)


@partial(jax.jit, static_argnames=("metric", "params_t"))
def _kernel_blocks(Xs, keep_idx, n_valid, *, metric, params_t):
    """Landmark Gram block A (l, l) and sharded kernel strip C (n_pad, l)
    with padding rows zeroed (so sample-axis degree sums stay exact) —
    the staging half of the fit, one jitted program."""
    params = dict(params_t)
    Xk = jnp.take(Xs, keep_idx, axis=0)  # (l, d), replicated by GSPMD
    A = pairwise_kernels(Xk, Xk, metric=metric, **params)
    C = pairwise_kernels(Xs, Xk, metric=metric, **params)
    row_valid = jnp.arange(C.shape[0]) < n_valid
    return A, jnp.where(row_valid[:, None], C, 0.0)


def _gram_colsum(C, mesh):
    """Column degree of the sharded kernel strip — the fit's one
    sample-axis collective. Hierarchical meshes stage it chip-then-pod
    through ``hpsum`` (ledger op ``kernel.gram.colsum``); flat meshes
    keep the plain GSPMD reduction and record the same logical bytes, so
    flat-vs-hierarchical per-op accounting covers the same reduction
    regardless of lowering (the ``fused.argmin_weight`` convention)."""
    if mesh is None:
        return jnp.sum(C, axis=0)
    from dask_ml_tpu.parallel.hierarchy import hpsum, record_collective
    from dask_ml_tpu.parallel.mesh import data_axes, is_hierarchical, \
        shard_map
    from jax.sharding import PartitionSpec as P

    if not is_hierarchical(mesh):
        record_collective("kernel.gram.colsum", mesh, (C.shape[1],),
                          jnp.float32)
        return jnp.sum(C, axis=0)
    axes = data_axes(mesh)
    a = axes[0] if len(axes) == 1 else axes
    fn = shard_map(
        lambda Cl: hpsum(jnp.sum(Cl, axis=0), mesh,
                         op="kernel.gram.colsum"),
        mesh=mesh, in_specs=(P(a, None),), out_specs=P(),
        check_vma=False)
    return fn(C)


@jax.jit
def _feature_core(A, C, colsum, keep_idx, n_true):
    """The post-collective Nyström feature math: unified degree
    normalization (the spectral ``_nystrom_core`` identities — keep rows
    of the strip ARE A's rows, so one formula covers all rows), the
    small replicated eigensolve, and the THRESHOLDED full-width
    whitening map. Returns ``(Φ (n_pad, l), extension factors)`` where
    the factors are exactly the ``_nystrom_map`` argument pack that
    ``predict`` replays on new rows."""
    A_inv = jnp.linalg.pinv(A)
    ainv_colsum = A_inv @ colsum  # (l,) degree functional
    d_all = C @ ainv_colsum  # (n_pad,) approximate row degrees
    d_si = 1.0 / jnp.sqrt(jnp.maximum(d_all, 1e-12))
    d1_si = jnp.take(d_si, keep_idx)  # landmark rows' exact degrees

    A2 = d1_si[:, None] * A * d1_si[None, :]
    U_A, S_A, _ = jnp.linalg.svd(A2)
    # full l-column whitening, trailing spectrum thresholded not inverted
    inv_sqrt = jnp.where(S_A > S_A[0] * 1e-6, 1.0 / jnp.sqrt(S_A), 0.0)
    map_full = U_A * inv_sqrt[None, :]  # (l, l)
    scale = jnp.sqrt(keep_idx.shape[0] / n_true)
    Phi = _nystrom_map(C, ainv_colsum, d1_si, map_full, scale,
                       row_normalize=False)
    return Phi, (ainv_colsum, d1_si, map_full)


@partial(jax.jit, static_argnames=("metric", "params_t", "mesh"))
def _kernel_assign_program(Xs, Xk, ainv_colsum, d1_si, map_full, scale,
                           centers, *, metric, params_t, mesh):
    """Out-of-sample kernel-k-means assignment as ONE jitted program:
    kernel strip against the fitted landmarks, the un-normalized Nyström
    feature extension, nearest-center assignment through the fused
    distance-reduction family — the kernel-k-means sibling of
    spectral's ``_nystrom_assign_program``, shared by :meth:`predict`
    and the serving runners (parallel/serving.py) so served labels are
    bit-identical to direct calls by construction."""
    from dask_ml_tpu.ops.fused_distance import fused_argmin_min

    C = pairwise_kernels(Xs, Xk, metric=metric, **dict(params_t))
    V = _nystrom_map(C, ainv_colsum, d1_si, map_full, scale,
                     row_normalize=False)
    labels, _ = fused_argmin_min(V, centers, mesh=mesh)
    return labels


class KernelKMeans(BaseEstimator, ClusterMixin):
    """Landmark (Nyström) kernel k-means — see the module docstring for
    the algorithm and how it shares seams with SpectralClustering and
    KMeans. String kernel names only (the jitted programs take the
    metric as a static argument; callables belong to the spectral eager
    path, which this estimator deliberately does not duplicate).

    Parameters follow :class:`SpectralClustering` where they overlap:
    ``n_components`` is the landmark count l, ``affinity``/``gamma``/
    ``degree``/``coef0``/``kernel_params`` the kernel, ``kmeans_params``
    forwards to the inner :class:`KMeans` that clusters the feature
    rows, and ``n_init`` runs that inner k-means from several seeds on
    the once-computed features, keeping the lowest-inertia run. Fitted attributes: ``labels_``, ``cluster_centers_`` (k, l —
    centers in FEATURE space), ``inertia_`` (feature-space SSE),
    ``n_iter_``, plus the landmark/extension state ``predict`` replays.
    """

    def __init__(self, n_clusters=8, n_components=100, affinity="rbf",
                 gamma=1.0, degree=3, coef0=1, kernel_params=None,
                 n_init=3, random_state=None, kmeans_params=None):
        self.n_clusters = n_clusters
        self.n_components = n_components
        self.affinity = affinity
        self.gamma = gamma
        self.degree = degree
        self.coef0 = coef0
        self.kernel_params = kernel_params
        self.n_init = n_init
        self.random_state = random_state
        self.kmeans_params = kmeans_params

    def _kernel_params(self) -> dict:
        params = dict(self.kernel_params or {})
        params["gamma"] = self.gamma
        params["degree"] = self.degree
        params["coef0"] = self.coef0
        return params

    def fit(self, X, y=None):
        if callable(self.affinity):
            raise ValueError(
                "KernelKMeans requires a string kernel name; callable "
                "affinities are supported by SpectralClustering's eager "
                "path")
        _check_affinity(self.affinity)
        X = check_array(X)
        n = int(X.shape[0])
        l = int(self.n_components)
        if n <= l:
            raise ValueError(
                "'n_components' must be smaller than the number of "
                f"samples. Got {l} components and {n} samples")
        rng = check_random_state_np(self.random_state)

        from dask_ml_tpu.parallel.mesh import default_mesh

        Xs, n_valid = shard_rows(X)
        log_array(logger, "kernel-kmeans: staged X", Xs)
        keep = rng.choice(np.arange(n), l, replace=False)
        keep.sort()
        params_t = tuple(sorted(self._kernel_params().items()))
        with telemetry.span("kernel-kmeans-nystrom",
                            landmarks=int(l), k=int(self.n_clusters)):
            A, C = _kernel_blocks(
                Xs, jnp.asarray(keep), jnp.asarray(n_valid, jnp.int32),
                metric=self.affinity, params_t=params_t)
            colsum = _gram_colsum(C, default_mesh())
            Phi, ext = _feature_core(
                A, C, colsum, jnp.asarray(keep),
                jnp.asarray(float(n), jnp.float32))
        # best-of-n_init restarts of the inner k-means: the feature rows
        # are computed once and stay on device, so extra inits cost only
        # the small (n, l) Lloyd loops — the whitened embedding has flat
        # directions that can trap a single init in a bad local minimum
        U = unpad_rows(Phi, n_valid)
        km = None
        for _ in range(max(1, int(self.n_init))):
            cand = KMeans(n_clusters=self.n_clusters,
                          random_state=rng.randint(2**31 - 1))
            if self.kmeans_params:
                cand.set_params(**self.kmeans_params)
            cand.fit(U)
            if km is None or cand.inertia_ < km.inertia_:
                km = cand

        self._landmarks_ = np.asarray(jnp.take(Xs, jnp.asarray(keep),
                                               axis=0))
        self._extension_ = tuple(np.asarray(e) for e in ext)
        self._n_fit_rows_ = float(n)
        self.assign_kmeans_ = km
        self.labels_ = np.asarray(km.labels_)
        self.cluster_centers_ = np.asarray(km.cluster_centers_)
        self.inertia_ = float(km.inertia_)
        self.n_iter_ = int(km.n_iter_)
        self.n_features_in_ = int(X.shape[1])
        return self

    def fit_predict(self, X, y=None):
        self.fit(X)
        return self.labels_

    def _assign_staged(self, Xs):
        """Labels for STAGED (padded, row-sharded) rows through the one
        jitted assignment program — PADDED device labels; callers slice
        to the true row count. Shared by :meth:`predict` and the serving
        batch runners."""
        from dask_ml_tpu.parallel.mesh import default_mesh

        ainv_colsum, d1_si, map_full = (
            jnp.asarray(e) for e in self._extension_)
        scale = jnp.asarray(
            np.sqrt(int(self.n_components) / self._n_fit_rows_),
            jnp.float32)
        return _kernel_assign_program(
            Xs, jnp.asarray(self._landmarks_), ainv_colsum, d1_si,
            map_full, scale, jnp.asarray(self.cluster_centers_),
            metric=self.affinity,
            params_t=tuple(sorted(self._kernel_params().items())),
            mesh=default_mesh())

    def predict(self, X):
        """Labels for NEW rows: kernel strip against the fitted
        landmarks, the same un-normalized extension the fit used
        (training rows re-extend to their fit features exactly), fused
        nearest-center assignment. Exact kernel k-means has no
        out-of-sample story; the landmark factorization gives one for
        free."""
        if not hasattr(self, "cluster_centers_"):
            raise AttributeError("Model not fitted; call fit first")
        X = check_array(X)
        from dask_ml_tpu.parallel import precision as precision_lib

        Xs, n_valid = shard_rows(
            X, dtype=precision_lib.staging_wire_dtype())
        return np.asarray(
            self._assign_staged(Xs))[:n_valid].astype(np.int32)
