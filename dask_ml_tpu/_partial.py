"""Deprecated ``Partial*`` subclass wrappers.

The reference ships a family of deprecated estimators — sklearn classes
subclassed with ``_BigPartialFitMixin`` so ``fit`` feeds data blocks to
``partial_fit`` sequentially (reference: _partial.py:40-101 the mixin,
cluster/minibatch.py:9-11, linear_model/stochastic_gradient.py:7-15,
perceptron.py:7-9, passive_aggressive.py:7-15, neural_network.py:7-13,
naive_bayes.py:123-132 the concrete wrappers). They predate ``Incremental``,
which supersedes them (reference deprecation notes point there); we keep them
for drop-in parity, with the same FutureWarning.

The rebuild's mixin drives :func:`dask_ml_tpu.wrappers.fit` (the sequential
block loop) instead of building a dask task chain; semantics are identical:
``classes``-style kwargs are accepted at construction and forwarded to every
``partial_fit`` call (reference: _partial.py:59-76).
"""

from __future__ import annotations

import warnings

from sklearn.base import BaseEstimator

from dask_ml_tpu import wrappers


class _BigPartialFitMixin(BaseEstimator):
    """Wrapper for estimators with ``partial_fit``
    (reference: _partial.py:40-101)."""

    _init_kwargs: list = []  # accepted at __init__, forwarded to partial_fit
    _fit_kwargs: list = []   # accepted at fit, forwarded to partial_fit

    def __init__(self, **kwargs):
        missing = set(self._init_kwargs) - set(kwargs)
        if missing:
            raise TypeError(
                f"{type(self).__name__} requires the keyword arguments "
                f"{sorted(missing)} at construction (forwarded to each "
                f"partial_fit call)"
            )
        for kwarg in self._init_kwargs:
            setattr(self, kwarg, kwargs.pop(kwarg))
        warnings.warn(
            f"'{type(self).__name__}' is deprecated, use "
            f"'dask_ml_tpu.wrappers.Incremental({self._base_name()}(...))' "
            "instead",
            FutureWarning,
        )
        super().__init__(**kwargs)

    @classmethod
    def _base_name(cls) -> str:
        for base in cls.__mro__:
            if (
                not issubclass(base, _BigPartialFitMixin)
                and issubclass(base, BaseEstimator)
                and base is not BaseEstimator
            ):
                return base.__name__
        return "Estimator"  # pragma: no cover

    @classmethod
    def _get_param_names(cls):
        """Underlying estimator's params + the extra init kwargs.

        Only the FIRST non-mixin base (the concrete sklearn estimator)
        contributes: walking the whole MRO like the reference does
        (reference: _partial.py:84-96) picks up constructor params of
        sklearn-internal bases — e.g. ``BaseSGD.__init__``'s ``C`` —
        that the public class rejects, which breaks ``clone()``."""
        bases = [
            base for base in cls.__mro__
            if not issubclass(base, _BigPartialFitMixin)
            and hasattr(base, "_get_param_names")
        ]
        params = set(cls._init_kwargs)
        if bases:
            params |= set(bases[0]._get_param_names())
        return sorted(params)

    def fit(self, X, y=None, block_size: int = wrappers.DEFAULT_BLOCK_SIZE):
        kwargs = {k: getattr(self, k) for k in self._init_kwargs}
        for k in self._fit_kwargs:
            if hasattr(self, k):
                kwargs[k] = getattr(self, k)
        wrappers.fit(self, X, y, block_size=block_size, **kwargs)
        return self


def _copy_partial_doc(cls):
    """Prefix the wrapped estimator's docstring with the deprecation banner
    (reference: _partial.py:208-230)."""
    base = cls.__mro__[2] if len(cls.__mro__) > 2 else cls
    cls.__doc__ = (
        f"Deprecated blockwise ``fit``-via-``partial_fit`` wrapper around "
        f"``{base.__module__}.{base.__name__}``; use "
        f"``dask_ml_tpu.wrappers.Incremental`` instead.\n\n"
        + (base.__doc__ or "")
    )
    return cls


# Functional surface parity (reference: _partial.py:104-182 ``fit``,
# :189-212 ``predict``): ``fit`` is the sequential partial_fit block chain
# (re-exported from wrappers, where the jax-native fused-scan fast path
# lives); ``predict`` applies a fitted model blockwise on the host.
from dask_ml_tpu.wrappers import DEFAULT_BLOCK_SIZE, fit  # noqa: F401,E402


def predict(model, x, block_size: int = DEFAULT_BLOCK_SIZE):
    """Blockwise predict with a fitted sklearn-style model
    (reference: _partial.py:189-212). The mesh-parallel inference path is
    :class:`dask_ml_tpu.wrappers.ParallelPostFit`; this is the plain
    host-block loop for reference-API compatibility."""
    import numpy as np

    if getattr(x, "ndim", 2) != 2:
        raise ValueError("predict expects a 2-D input")
    n = int(x.shape[0])
    parts = [
        model.predict(x[i:i + block_size]) for i in range(0, n, block_size)
    ]
    if not parts:
        # zero-row input is legal: let the model shape/type the empty
        # output (preserves n_targets and label dtype); fall back to a
        # bare empty array for models that reject empty batches
        try:
            return np.asarray(model.predict(x[:0]))
        except Exception:
            return np.empty((0,))
    return np.concatenate([np.asarray(p) for p in parts])
