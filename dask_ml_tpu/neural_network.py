"""Deprecated Partial MLP wrappers (reference: neural_network.py:7-13;
the reference's class names carry a ``Parital`` typo — we export the
corrected names and alias the typo'd ones for drop-in parity)."""

from __future__ import annotations

from sklearn.neural_network import MLPClassifier as _MLPClassifier
from sklearn.neural_network import MLPRegressor as _MLPRegressor

from dask_ml_tpu._partial import _BigPartialFitMixin, _copy_partial_doc


@_copy_partial_doc
class PartialMLPClassifier(_BigPartialFitMixin, _MLPClassifier):
    _init_kwargs = ["classes"]
    _fit_kwargs = []


@_copy_partial_doc
class PartialMLPRegressor(_BigPartialFitMixin, _MLPRegressor):
    pass


# reference-spelling aliases (neural_network.py:7,11)
ParitalMLPClassifier = PartialMLPClassifier
ParitalMLPRegressor = PartialMLPRegressor
