"""Drop-in GridSearchCV / RandomizedSearchCV as a host-side work-sharing driver.

The reference's biggest subsystem is a small query compiler: ``build_graph``
assembles one dask dict for the whole CV search, dedupes identical
(estimator-config, data) fits via content-addressed keys, recursively expands
``sklearn.Pipeline`` so shared prefixes are fit once, and hands the graph to a
pluggable scheduler (reference: model_selection/_search.py:89-160, 281-345,
462-503, 841-852).

The TPU-native shape of the same capability: there is no task graph — compute
inside an estimator's ``fit`` is already one XLA program over the mesh — so
the search layer becomes a **host-side thread-pool driver** with a
future-based memo table:

- work-sharing/CSE: each pipeline stage fit is keyed by
  ``token(stage-config, upstream-token, split-id)`` and computed exactly once
  no matter how many candidates share it (the analogue of the reference's
  ``seen`` maps, _search.py:281-345); identical whole candidates dedupe the
  same way.
- parallelism: independent candidate×split fits run concurrently on host
  threads. Heavy JAX work releases the GIL during device execution, and plain
  sklearn estimators (the heterogeneous path) parallelize exactly as they did
  under the reference's threaded scheduler.
- ``error_score``/``FIT_FAILURE`` semantics, ``cv_results_`` structure, iid
  weighting, multimetric + refit: see :mod:`.methods`.
"""

from __future__ import annotations

import numbers
import threading
from concurrent.futures import Future, ThreadPoolExecutor
from contextlib import contextmanager
from typing import Optional

import numpy as np
from sklearn.base import BaseEstimator, MetaEstimatorMixin, is_classifier
from sklearn.model_selection import ParameterGrid, ParameterSampler
from sklearn.pipeline import FeatureUnion, Pipeline

from dask_ml_tpu.model_selection import methods
from dask_ml_tpu.model_selection._split import check_cv
from dask_ml_tpu.model_selection._tokenize import tokenize
from dask_ml_tpu.model_selection.methods import FIT_FAILURE
from dask_ml_tpu.parallel import telemetry

__all__ = ["GridSearchCV", "RandomizedSearchCV", "TPUBaseSearchCV"]


# ---------------------------------------------------------------------------
# data slicing / caching (the reference's CVCache, methods.py:67-124)
# ---------------------------------------------------------------------------


def run_with_soft_deadline(fn, timeout, *, caller_cfg=None,
                           name="search-cell"):
    """Run ``fn()`` under a soft daemon-thread deadline: the caller waits
    at most ``timeout`` seconds, then abandons the thread (threads cannot
    be killed — the stray computation finishes in the background but no
    longer blocks the run). Returns ``(value, timed_out)``; exceptions
    from ``fn`` re-raise on the caller. A falsy ``timeout`` runs inline.

    ``caller_cfg`` (a :func:`dask_ml_tpu.config.get_config` subset) is
    re-entered on the deadline thread — config is thread-local, so the
    caller's dtype/staging knobs must travel with the work.

    One timeout discipline, two consumers: the grid/random driver's
    per-CELL deadline (below) and the incremental ASHA driver's per-RUNG
    deadline (``_incremental.py``), whose contract differs only in what a
    timeout means — error_score for a cell, *degrade to the last
    completed rung score* for a streaming candidate.
    """
    if not timeout:
        return fn(), False
    from dask_ml_tpu import config as config_lib

    box: dict = {}

    def target():
        # config is thread-local: the deadline thread re-enters it
        try:
            if caller_cfg is None:
                box["result"] = fn()
            else:
                with config_lib.config_context(**caller_cfg):
                    box["result"] = fn()
        except BaseException as e:  # re-raised on the caller
            box["error"] = e

    t = threading.Thread(target=target, daemon=True, name=name)
    t.start()
    t.join(float(timeout))
    if t.is_alive():
        return None, True
    if "error" in box:
        raise box["error"]
    return box["result"], False


def _is_pairwise(est) -> bool:
    try:
        return bool(est.__sklearn_tags__().input_tags.pairwise)
    except Exception:
        return bool(getattr(est, "_pairwise", False))


def _n_rows(a) -> int:
    """Sample count for any X container (ndarray, scipy sparse, frame,
    list) — ``np.asarray(sparse)`` would 0-d wrap it."""
    shape = getattr(a, "shape", None)
    if shape is not None and len(shape) >= 1:
        return int(shape[0])
    return len(a)


def _index(a, idx):
    if a is None:
        return None
    if hasattr(a, "iloc"):
        return a.iloc[idx]
    if hasattr(a, "tocsr"):  # scipy sparse: np.asarray would 0-d wrap it
        return a.tocsr()[idx]
    from dask_ml_tpu.ops.sparse import SparseRows

    if isinstance(a, SparseRows):  # sparse container: row-gather both
        return a[idx]              # leaves (np.asarray would 0-d wrap it)
    return np.asarray(a)[idx]


def _content_array(a):
    """A content-hashable stand-in for checkpoint keys: numeric data as the
    actual array (tokenize hashes its bytes), object-dtype / exotic inputs as
    their pickle bytes — so journal keys change whenever data VALUES change,
    not just shapes."""
    if a is None:
        return None
    try:
        arr = np.asarray(a)
    except Exception:
        arr = None
    if arr is not None and arr.dtype != object:
        return arr
    import pickle

    try:
        return pickle.dumps(a, protocol=4)
    except Exception:
        return repr(a)


class CVCache:
    """Materialized train/test slices per split, cached per search
    (reference: methods.py:67-124). ``extract(..., pairwise=True)`` slices
    both axes of a precomputed kernel matrix the way the reference does
    (methods.py:110-124).

    ``device_slices=True`` (set by the driver for all-jax-native candidate
    estimators): X uploads to the device ONCE and train/test slices are
    device-side gathers — over a slow host link, uploading every CV slice
    separately costs ~2× the bytes of X per split pair, all on the wire.
    y and pairwise-kernel slicing stay host-side (small / special-cased).

    ``pad_policy`` (a :class:`~dask_ml_tpu.parallel.shapes.PadPolicy`, or
    None) is the shape-bucketing policy the slices will be staged under by
    their consumers: extract() itself returns EXACT slices (the padding —
    weight-0 rows up to the bucket — happens inside each estimator's
    ``prepare_data``, which is also what keeps the padded rows inert), but
    the cache knows the plan, and :meth:`planned_buckets` reports which
    padded sizes the search's fold slices share — the bound the compile-
    count CI gate asserts against and ``bench.py --compile-report``
    records as ``shape_buckets``.
    """

    def __init__(self, splits, X, y, cache: bool = True,
                 device_slices: bool = False, pad_policy=None):
        self.splits = list(splits)
        self.X = X
        self.y = y
        self.cache = {} if cache else None
        self._x_dev = None
        self._dev_lock = threading.Lock()
        self.device_slices = bool(device_slices) and self._device_sliceable(X)
        self.pad_policy = pad_policy

    @staticmethod
    def _device_sliceable(X) -> bool:
        if X is None or hasattr(X, "iloc"):
            return False
        try:
            arr = np.asarray(X)
        except Exception:
            return False
        return arr.ndim == 2 and arr.dtype.kind in "fiub"

    def _device_slice(self, idx):
        import jax.numpy as jnp

        from dask_ml_tpu.parallel.sharding import _current_memo
        from dask_ml_tpu.utils.validation import staging_dtype

        with self._dev_lock:
            if self._x_dev is None:
                arr = np.asarray(self.X)
                x = jnp.asarray(arr, dtype=staging_dtype(arr.dtype))
                # One NaN/inf scan for the WHOLE search at upload: finite
                # data marks its slices trusted (estimators skip the
                # per-stage re-scan). Non-finite data is NOT an error
                # here — slices stay untrusted, each estimator's own
                # check_array raises inside methods.fit, and the cells
                # follow error_score semantics exactly as host slicing did.
                from dask_ml_tpu.utils.validation import _all_finite

                self._x_finite = bool(_all_finite(x))
                self._x_dev = x
        out = jnp.take(self._x_dev, jnp.asarray(np.asarray(idx)), axis=0)
        memo = _current_memo()
        if memo is not None and self._x_finite:
            memo.trust(out)
        return out

    def n_test(self, split_idx: int) -> int:
        return len(self.splits[split_idx][1])

    def planned_buckets(self) -> list:
        """Sorted padded sample counts the fold slices land in when staged
        under ``pad_policy`` on the current mesh. K folds whose train sizes
        differ by a row share a bucket, so a P-candidate × K-fold search
        compiles O(len(planned_buckets())) data-shaped programs, not O(K)
        per batched group — the invariant the CI ``compile`` job gates."""
        from dask_ml_tpu.parallel import mesh as mesh_lib
        from dask_ml_tpu.parallel import shapes

        align = mesh_lib.n_data_shards(mesh_lib.default_mesh())
        sizes = set()
        for train_idx, test_idx in self.splits:
            for idx in (train_idx, test_idx):
                # record=False: this is a PLAN query — only actual staging
                # may write compile_stats()['shape_buckets']
                sizes.add(shapes.bucket_rows(len(idx), align=align,
                                             policy=self.pad_policy,
                                             record=False))
        return sorted(sizes)

    def extract(self, split_idx: int, train: bool, is_x: bool = True,
                pairwise: bool = False):
        key = (split_idx, train, is_x, pairwise)
        if self.cache is not None and key in self.cache:
            return self.cache[key]
        train_idx, test_idx = self.splits[split_idx]
        idx = train_idx if train else test_idx
        if not is_x:
            out = _index(self.y, idx)
        elif pairwise:
            X = np.asarray(self.X)
            if X.ndim != 2 or X.shape[0] != X.shape[1]:
                raise ValueError(
                    "X should be a square kernel matrix for pairwise "
                    "estimators"
                )
            out = X[np.ix_(idx, train_idx)]
        elif self.device_slices:
            out = self._device_slice(idx)
        else:
            out = _index(self.X, idx)
        if self.cache is not None:
            self.cache[key] = out
        return out


# ---------------------------------------------------------------------------
# future-based memo (the analogue of graph-key CSE)
# ---------------------------------------------------------------------------


class _Memo:
    """token → Future; the first thread to claim a token computes it, every
    other candidate sharing the token waits on the same future. This gives the
    reference's graph-level CSE (one task per distinct key) under threads.

    Each entry also records a human label, its upstream keys, and how many
    cells consumed it — the data behind ``shared_fit_report()`` /
    ``visualize()`` (the reference's ``GridSearchCV.visualize`` renders the
    shared-fit dask graph the same way, _search.py:870-894)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._futures: dict[str, Future] = {}
        self._meta: dict[str, dict] = {}
        self._peek_depth = 0

    @contextmanager
    def peek_scope(self):
        """Scope whose ``get_or_run`` calls don't count as consumers — the
        driver's batched-group pre-pass dispatches every group program for
        bulk-fetching, but only CELLS consume results; counting the
        pre-pass would inflate ``shared_fit_report``/``visualize`` by one
        per (group, split) and per pre-fetched prefix node. Single-threaded
        use only (the pre-pass runs before the worker pool starts)."""
        self._peek_depth += 1
        try:
            yield
        finally:
            self._peek_depth -= 1

    def get_or_run(self, key: str, fn, label: Optional[str] = None,
                   parents: tuple = ()):
        with self._lock:
            meta = self._meta.setdefault(
                key, {"label": label, "parents": tuple(parents),
                      "consumers": 0})
            if not self._peek_depth:
                meta["consumers"] += 1
            if label and not meta["label"]:
                meta["label"] = label
            fut = self._futures.get(key)
            owner = fut is None
            if owner:
                fut = Future()
                self._futures[key] = fut
        if owner:
            try:
                fut.set_result(fn())
            except BaseException as e:  # error_score='raise' path
                fut.set_exception(e)
        return fut.result()

    @property
    def n_entries(self) -> int:
        return len(self._futures)

    def report(self) -> dict:
        with self._lock:
            return {k: dict(m) for k, m in self._meta.items()}


# ---------------------------------------------------------------------------
# scoring resolution
# ---------------------------------------------------------------------------


def _passthrough_scorer(est, X, y):
    return est.score(X, y)


def _scoring_identity(scoring):
    """Content identity of a ``scoring=`` spec, for checkpoint cell keys.

    Journal records must invalidate when the scoring CHANGES, including a
    different custom callable under the same slot name (ADVICE r3: keying on
    ``sorted(scorers)`` alone restored stale scores after swapping a scorer).
    String specs identify by name; callables by code/attribute content
    (bytecode + global names + consts + closure values — see
    ``_tokenize._callable_identity``), which is stable across processes AND
    changes when the scorer's implementation changes — pickle bytes would do
    neither for module-level functions (serialized by reference) or lambdas
    (unpicklable).
    """
    from dask_ml_tpu.model_selection._tokenize import (_callable_identity,
                                                       _stable_repr)

    if scoring is None or isinstance(scoring, str):
        return ("named", scoring)
    if callable(scoring):
        return _callable_identity(scoring)
    if isinstance(scoring, (list, tuple, set)):
        return ("list", tuple(sorted(scoring)))
    if isinstance(scoring, dict):
        return ("dict", tuple(
            (name, _scoring_identity(s))
            for name, s in sorted(scoring.items())
        ))
    return ("repr", _stable_repr(scoring))


def _lookup_scorer(name: str):
    from dask_ml_tpu.metrics.scorer import get_scorer

    return get_scorer(name)


def _resolve_scoring(estimator, scoring):
    """→ (scorers: {name: callable}, multimetric: bool).

    Mirrors the reference's scorer setup incl. multimetric
    (reference: _search.py:789-818)."""
    if scoring is None:
        if not hasattr(estimator, "score"):
            raise TypeError(
                f"estimator {estimator!r} has no score method; pass scoring="
            )
        return {"score": _passthrough_scorer}, False
    if isinstance(scoring, str):
        return {"score": _lookup_scorer(scoring)}, False
    if callable(scoring):
        return {"score": scoring}, False
    if isinstance(scoring, (list, tuple, set)):
        names = list(scoring)
        if len(set(names)) != len(names):
            raise ValueError(f"Duplicate scorer names in {names!r}")
        if not all(isinstance(n, str) for n in names):
            raise ValueError(
                "multimetric scoring as a list requires string names"
            )
        return {n: _lookup_scorer(n) for n in names}, True
    if isinstance(scoring, dict):
        return (
            {
                n: (_lookup_scorer(s) if isinstance(s, str) else s)
                for n, s in scoring.items()
            },
            True,
        )
    raise ValueError(f"Invalid scoring: {scoring!r}")


# ---------------------------------------------------------------------------
# candidate execution with pipeline-prefix sharing
# ---------------------------------------------------------------------------


def _split_pipeline_params(steps, params):
    """Partition candidate params into per-stage dicts keyed by stage name;
    top-level (non-prefixed) params are rejected the way set_params would be."""
    names = [name for name, _ in steps]
    per_stage = {name: {} for name in names}
    top = {}
    for key, value in params.items():
        if "__" in key:
            stage, _, sub = key.partition("__")
            if stage in per_stage:
                per_stage[stage][sub] = value
                continue
        top[key] = value
    return per_stage, top


def _is_dropped(trans) -> bool:
    return trans is None or trans == "drop"


def _union_concat(parts, weights, n_rows):
    """Weighted horizontal concat of sub-transformer outputs, matching
    sklearn's ``FeatureUnion.transform`` (and the reference's
    ``feature_union_concat``, methods.py:179-187)."""
    arrays = []
    for name, Xt in parts:
        w = (weights or {}).get(name)
        arrays.append(Xt if w is None else np.asarray(Xt) * w)
    if not arrays:
        return np.zeros((n_rows, 0))
    try:
        from scipy import sparse

        if any(sparse.issparse(a) for a in arrays):
            return sparse.hstack(arrays).tocsr()
    except ImportError:  # pragma: no cover
        pass
    return np.hstack([np.asarray(a) for a in arrays])


class _CandidateRunner:
    """Executes one (candidate, split) cell with memoized stage fits."""

    def __init__(self, estimator, cv_cache: CVCache, memo: _Memo, scorers,
                 error_score, return_train_score: bool, fit_params=None,
                 retry_policy=None):
        self.estimator = estimator
        self.cv_cache = cv_cache
        self.memo = memo
        self.scorers = scorers
        self.error_score = error_score
        self.return_train_score = return_train_score
        self.fit_params = fit_params or {}
        # transient-error retry for cell fits (parallel/faults.RetryPolicy):
        # a flaky-I/O or device-transfer failure re-attempts from a fresh
        # estimator copy before degrading to error_score semantics
        self.retry_policy = retry_policy
        self._n_samples = (
            None if cv_cache.X is None else _n_rows(cv_cache.X)
        )
        self._fp_cache: dict[int, dict] = {}
        self._fp_lock = threading.Lock()
        self.n_batched_done = 0  # cells that actually took the batched path
        self._batched_lock = threading.Lock()

    def _fit_params_for(self, split_idx):
        """Per-split fit params: array-likes aligned with the sample axis are
        sliced by the split's train indices (sklearn's _check_method_params
        behavior); everything else passes through whole."""
        if not self.fit_params:
            return {}
        with self._fp_lock:
            if split_idx in self._fp_cache:
                return self._fp_cache[split_idx]
        train_idx, _ = self.cv_cache.splits[split_idx]
        out = {}
        for name, value in self.fit_params.items():
            if (
                hasattr(value, "__len__")
                and not isinstance(value, str)
                and self._n_samples is not None
                and len(value) == self._n_samples
            ):
                out[name] = _index(value, train_idx)
            else:
                out[name] = value
        with self._fp_lock:
            self._fp_cache[split_idx] = out
        return out

    # -- plain estimator -------------------------------------------------
    def _fit_plain(self, params, split_idx):
        est = self.estimator
        pairwise = _is_pairwise(est)
        key = tokenize("fit", type(est), est.get_params(deep=True),
                       params, sorted(self.fit_params), split_idx, pairwise)

        def run():
            X = self.cv_cache.extract(split_idx, train=True, pairwise=pairwise)
            y = self.cv_cache.extract(split_idx, train=True, is_x=False)
            return methods.fit(
                est, X, y, params=params,
                fit_params=self._fit_params_for(split_idx),
                error_score=self.error_score,
                retry_policy=self.retry_policy,
            )

        return self.memo.get_or_run(
            key, run, label=f"fit:{type(est).__name__}")

    # -- recursive composite expansion with CSE --------------------------
    #
    # Pipelines and FeatureUnions are expanded recursively so every leaf
    # transformer fit is its own memo entry: pipeline prefixes are shared
    # across candidates (reference: _search.py:462-503 ``_do_pipeline``) and
    # union sub-transformers are shared across candidates *including ones that
    # differ only in transformer_weights*, because weights apply at the concat
    # step, not the fit (reference: _search.py:524-593 ``_do_featureunion``,
    # methods.py:169-187).

    def _root_token(self, split_idx):
        return tokenize("pipe-root", split_idx)

    def _resolve_input(self, upstream, split_idx, root_pairwise: bool = False):
        """Train-side input identified by ``upstream``: the original slice at
        the root token, else the transformed output stored in the upstream
        node's memo entry. Safe to read here: any thread reaching node *i+1*
        already passed through node *i*'s ``get_or_run`` in its own recursion,
        so the upstream future exists and resolving it cannot race."""
        if upstream == self._root_token(split_idx):
            return self.cv_cache.extract(split_idx, train=True,
                                         pairwise=root_pairwise)

        def missing():  # pragma: no cover - ordering invariant
            raise RuntimeError("upstream node output missing")

        (_, Xt), _t = self.memo.get_or_run(upstream, missing)
        return Xt

    def _y_train(self, split_idx):
        return self.cv_cache.extract(split_idx, train=True, is_x=False)

    def _fit_transform_any(self, est, params, sfit, upstream, split_idx,
                           root_pairwise=False):
        """Fit+transform a node in the composite tree.
        Returns ``(token, fitted, Xt, fit_time, failed)``; ``token`` has a
        memo entry of shape ``((fitted, Xt), time)`` so it can serve as the
        ``upstream`` of downstream nodes."""
        if isinstance(est, Pipeline):
            return self._ft_pipeline(est, params, sfit, upstream, split_idx,
                                     root_pairwise, need_transform=True)
        if isinstance(est, FeatureUnion):
            return self._ft_union(est, params, sfit, upstream, split_idx,
                                  root_pairwise, need_transform=True)
        key = tokenize("stage", upstream, type(est),
                       est.get_params(deep=True), params, sorted(sfit), "ft")

        def run_stage():
            Xin = self._resolve_input(upstream, split_idx, root_pairwise)
            return methods.fit_transform(
                est, Xin, self._y_train(split_idx), params=params,
                fit_params=sfit, error_score=self.error_score,
                retry_policy=self.retry_policy,
            )

        (fitted, Xt), t = self.memo.get_or_run(
            key, run_stage, label=f"fit_transform:{type(est).__name__}",
            parents=(upstream,))
        return key, fitted, Xt, t, fitted is FIT_FAILURE

    def _fit_any(self, est, params, sfit, upstream, split_idx,
                 root_pairwise=False):
        """Fit-only variant (terminal nodes: the last pipeline stage, or the
        search estimator itself). Returns ``(token, fitted, fit_time,
        failed)``."""
        if isinstance(est, Pipeline):
            token, fitted, _Xt, t, failed = self._ft_pipeline(
                est, params, sfit, upstream, split_idx, root_pairwise,
                need_transform=False,
            )
            return token, fitted, t, failed
        if isinstance(est, FeatureUnion):
            token, fitted, _Xt, t, failed = self._ft_union(
                est, params, sfit, upstream, split_idx, root_pairwise,
                need_transform=False,
            )
            return token, fitted, t, failed
        key = tokenize("stage", upstream, type(est),
                       est.get_params(deep=True), params, sorted(sfit), "fit")

        def run_fit():
            Xin = self._resolve_input(upstream, split_idx, root_pairwise)
            return methods.fit(
                est, Xin, self._y_train(split_idx), params=params,
                fit_params=sfit, error_score=self.error_score,
                retry_policy=self.retry_policy,
            )

        fitted, t = self.memo.get_or_run(
            key, run_fit, label=f"fit:{type(est).__name__}",
            parents=(upstream,))
        return key, fitted, t, fitted is FIT_FAILURE

    def _ft_atomic_fallback(self, est, params, sfit, upstream, split_idx,
                            root_pairwise, need_transform):
        """Whole-object fit for composites whose candidate params target the
        composite itself (e.g. ``steps=``/``transformer_list=`` overrides):
        no sub-sharing is possible, same fallback the reference takes."""
        mode = "ft" if need_transform else "fit"
        key = tokenize("whole", upstream, type(est),
                       est.get_params(deep=True), params, sorted(sfit), mode)

        def run_whole():
            Xin = self._resolve_input(upstream, split_idx, root_pairwise)
            y = self._y_train(split_idx)
            if need_transform:
                return methods.fit_transform(
                    est, Xin, y, params=params, fit_params=sfit,
                    error_score=self.error_score,
                    retry_policy=self.retry_policy,
                )
            return methods.fit(
                est, Xin, y, params=params, fit_params=sfit,
                error_score=self.error_score,
                retry_policy=self.retry_policy,
            )

        wl = f"whole-{mode}:{type(est).__name__}"
        if need_transform:
            (fitted, Xt), t = self.memo.get_or_run(
                key, run_whole, label=wl, parents=(upstream,))
        else:
            fitted, t = self.memo.get_or_run(
                key, run_whole, label=wl, parents=(upstream,))
            Xt = None
        return key, fitted, Xt, t, fitted is FIT_FAILURE

    def _ft_pipeline(self, pipe, params, sfit, upstream, split_idx,
                     root_pairwise, need_transform):
        per_stage, top = _split_pipeline_params(pipe.steps, params)
        per_stage_fp, top_fp = _split_pipeline_params(pipe.steps, sfit)
        if top or top_fp:
            return self._ft_atomic_fallback(
                pipe, params, sfit, upstream, split_idx, root_pairwise,
                need_transform,
            )
        token = upstream
        fitted_steps = []
        total_time = 0.0
        failed = False
        Xt = None
        for i, (name, stage) in enumerate(pipe.steps):
            if _is_dropped(stage) or stage == "passthrough":
                # identity stage: downstream input IS the upstream data, so
                # the token must stay unchanged (it has a resolvable memo
                # entry / root slice; a synthetic re-token would not)
                fitted_steps.append((name, stage))
                continue
            sparams = per_stage[name]
            stage_fp = per_stage_fp.get(name) or {}
            is_last = i == len(pipe.steps) - 1
            if is_last and not need_transform:
                token, fitted, t, f = self._fit_any(
                    stage, sparams, stage_fp, token, split_idx, root_pairwise)
            else:
                token, fitted, Xt, t, f = self._fit_transform_any(
                    stage, sparams, stage_fp, token, split_idx, root_pairwise)
            total_time += t
            if f:
                failed = True
                fitted_steps.append((name, FIT_FAILURE))
                break
            fitted_steps.append((name, fitted))
        if failed:
            return token, FIT_FAILURE, FIT_FAILURE, total_time, True
        out = methods.copy_estimator(pipe)
        out.steps = fitted_steps
        if need_transform and Xt is None:
            # every stage was identity (passthrough/dropped): the pipeline's
            # transform output IS its input — resolve it so a FeatureUnion
            # parent has a real array to concatenate, like sklearn's
            # identity branch
            Xt = self._resolve_input(token, split_idx, root_pairwise)
        # `token` is the last real stage's token; its memo entry already holds
        # Xt, but for a fit-only tail there is no transform output to expose.
        return token, out, Xt, total_time, False

    _UNION_SELF_PARAMS = ("n_jobs", "verbose", "verbose_feature_names_out")

    def _ft_union(self, union, params, sfit, upstream, split_idx,
                  root_pairwise, need_transform):
        per_sub, top = _split_pipeline_params(union.transformer_list, params)
        per_sub_fp, top_fp = _split_pipeline_params(union.transformer_list, sfit)
        top = dict(top)
        weights = union.transformer_weights
        if "transformer_weights" in top:
            weights = top.pop("transformer_weights")
        self_params = {
            k: top.pop(k) for k in list(top) if k in self._UNION_SELF_PARAMS
        }
        if top or top_fp:
            # e.g. transformer_list= overrides, or params for an unknown name
            return self._ft_atomic_fallback(
                union, params, sfit, upstream, split_idx, root_pairwise,
                need_transform,
            )

        sub_tokens = []
        sub_fitted = []
        sub_parts = []  # (name, Xt) for concat, transform-producing subs only
        total_time = 0.0
        failed = False
        for name, trans in union.transformer_list:
            if _is_dropped(trans):
                sub_tokens.append("drop")
                sub_fitted.append((name, trans))
                continue
            if trans == "passthrough":
                # identity member (sklearn accepts the sentinel here too):
                # contributes the union's INPUT columns unchanged. Candidate
                # params targeting it cannot apply — hard error, as
                # sklearn's set_params would raise (never a silent drop
                # that would also collapse distinct candidates' memo keys)
                stray = dict(per_sub.get(name) or {})
                stray.update(per_sub_fp.get(name) or {})
                if stray:
                    raise ValueError(
                        f"parameters {sorted(stray)} target union member "
                        f"'{name}', which is 'passthrough'"
                    )
                sub_tokens.append(upstream)
                sub_fitted.append((name, trans))
                if need_transform:
                    sub_parts.append((name, self._resolve_input(
                        upstream, split_idx, root_pairwise)))
                continue
            if need_transform:
                tok, fitted, Xt, t, f = self._fit_transform_any(
                    trans, per_sub[name], per_sub_fp.get(name) or {},
                    upstream, split_idx, root_pairwise,
                )
                sub_parts.append((name, Xt))
            else:
                tok, fitted, t, f = self._fit_any(
                    trans, per_sub[name], per_sub_fp.get(name) or {},
                    upstream, split_idx, root_pairwise,
                )
            total_time += t
            failed = failed or f
            sub_tokens.append(tok)
            sub_fitted.append((name, fitted))

        wkey = sorted(weights.items()) if weights else None
        mode = "ft" if need_transform else "fit"
        ckey = tokenize("union-concat", sub_tokens, wkey,
                        sorted(self_params.items()), mode)

        def assemble():
            if failed:
                return (FIT_FAILURE, FIT_FAILURE), 0.0
            out = methods.copy_estimator(union)
            if self_params:
                out.set_params(**self_params)
            out.transformer_list = list(sub_fitted)
            out.transformer_weights = weights
            Xt = None
            if need_transform:
                n_rows = len(
                    np.asarray(
                        self._resolve_input(upstream, split_idx, root_pairwise)
                    )
                )
                Xt = _union_concat(sub_parts, weights, n_rows)
            return (out, Xt), 0.0

        (fitted_union, Xt), t_assemble = self.memo.get_or_run(
            ckey, assemble, label="union-concat",
            parents=tuple(t for t in sub_tokens if t != "drop"))
        total_time += t_assemble
        return (ckey, fitted_union, Xt, total_time,
                fitted_union is FIT_FAILURE)

    # -- batched candidate cells (fast path) -----------------------------
    #
    # Homogeneous candidates (same estimator class, same static params,
    # same upstream pipeline prefix) are fit+scored as ONE compiled program
    # via the terminal estimator's ``_batched_fit_score`` protocol — the
    # "vmap over candidates" promise of SURVEY §2.9, and the answer to a
    # search paying per-cell dispatch + score-fetch round-trips on a
    # high-RTT host↔device link. The memo makes the group program run
    # exactly once however many member cells land on the pool.

    def _prefix_root_pairwise(self, est):
        if not isinstance(est, Pipeline):
            return _is_pairwise(est)
        first_real = next(
            (s for _, s in est.steps
             if not _is_dropped(s) and s != "passthrough"),
            None,
        )
        return _is_pairwise(first_real) if first_real is not None else False

    _PREFIX_FAILED = "prefix-failed"

    def batched_group_out(self, params, split_idx, group):
        """Dispatch (or memo-hit) a group's fit+score program.

        Returns ``(result, t_prefix)`` where ``result`` is
        ``(out_dict, t_group)``, ``None`` (group program failed under a
        numeric error_score), or ``_PREFIX_FAILED``. ``out_dict['scores']``
        may hold device arrays: the protocol's batched fits are pure async
        dispatch, and the driver pre-pass bulk-fetches every group's
        outputs in ONE ``device_get`` before cells read member values —
        per-group fetches each pay ~2 RTT and serialize on a tunneled
        host link."""
        from timeit import default_timer

        est = self.estimator
        root_pairwise = self._prefix_root_pairwise(est)
        t_prefix = 0.0
        if isinstance(est, Pipeline):
            term_name, term_est = est.steps[-1]
            prefix_steps = est.steps[:-1]
            root = self._root_token(split_idx)
            prefix_params = {
                k: v for k, v in params.items()
                if not k.startswith(term_name + "__")
            }
            if prefix_steps:
                # the prefix fits through the SAME recursive CSE machinery
                # (and thus the same memo tokens) as unbatched candidates
                token, fitted_prefix, Xt, t_prefix, failed = (
                    self._ft_pipeline(
                        Pipeline(prefix_steps), prefix_params, {}, root,
                        split_idx, root_pairwise, need_transform=True,
                    ))
                if failed:
                    return self._PREFIX_FAILED, t_prefix
            else:
                token = root
                Xt = self._resolve_input(root, split_idx, root_pairwise)
                fitted_prefix = None
        else:
            term_est = est
            token = self._root_token(split_idx)
            Xt = self.cv_cache.extract(split_idx, train=True,
                                       pairwise=root_pairwise)
            fitted_prefix = None

        def compute_test_input():
            Xe = self.cv_cache.extract(split_idx, train=False,
                                       pairwise=root_pairwise)
            if fitted_prefix is not None:
                for _name, stage in fitted_prefix.steps:
                    if _is_dropped(stage) or stage == "passthrough":
                        continue
                    Xe = stage.transform(Xe)
            return Xe

        test_key = tokenize("batch-test-input", token, split_idx)
        X_test = self.memo.get_or_run(
            test_key, compute_test_input, label="batch-test-input",
            parents=(token,))

        gkey = tokenize(
            "batch-cells", token, split_idx, type(term_est),
            term_est.get_params(deep=True), sorted(group.static.items()),
            group.token, self.return_train_score,
        )

        def run_group():
            t0 = default_timer()
            y_test = self.cv_cache.extract(split_idx, train=False,
                                           is_x=False)
            evals = [(X_test, y_test)]
            if self.return_train_score:
                evals.append((Xt, self._y_train(split_idx)))

            def attempt():
                # fresh copy per attempt: a transient failure mid-program
                # must not leak partially-mutated estimator state (e.g.
                # classes_ set by _encode_y) into the retry
                est_c = methods.copy_estimator(term_est)
                if group.static:
                    est_c.set_params(**group.static)
                return est_c._batched_fit_score(
                    Xt, self._y_train(split_idx), group.members, evals)

            try:
                if self.retry_policy is None:
                    out = attempt()
                else:
                    out = self.retry_policy.run(
                        attempt, kind="search-fit",
                        detail=f"batch:{type(term_est).__name__}")
            except Exception as e:
                if self.error_score == "raise":
                    raise
                methods.warn_fit_failure(self.error_score, e)
                return None  # whole-group failure
            if out is NotImplemented:
                # the estimator declined at runtime (e.g. the program's
                # memory footprint): members run per-cell instead
                return NotImplemented
            return out, default_timer() - t0

        result = self.memo.get_or_run(
            gkey, run_group,
            label=(f"batch-cells:{type(term_est).__name__}"
                   f"[{len(group.members)} members]"),
            parents=(token,))
        return result, t_prefix

    def run_batched(self, params, split_idx, group, member_idx):
        """One cell through its batch group. Same result contract as
        :meth:`run`; the group fit+score executes once per (group, split)."""
        result, t_prefix = self.batched_group_out(params, split_idx, group)
        if result is NotImplemented:
            # runtime decline by the estimator: the per-cell path still
            # shares prefix fits through the same memo tokens
            return self.run(params, split_idx)
        if result is self._PREFIX_FAILED or result is None:
            test, train, score_time = methods.score(
                FIT_FAILURE, None, None,
                None if not self.return_train_score else FIT_FAILURE,
                None, self.scorers, self.error_score)
            return test, train, t_prefix, score_time, True
        out, t_group = result
        with self._batched_lock:
            self.n_batched_done += 1
        n_members = max(len(group.members), 1)
        test = {"score": float(np.asarray(out["scores"][0][member_idx]))}
        train = None
        if self.return_train_score:
            train = {"score": float(np.asarray(out["scores"][1][member_idx]))}
        # wall-time attribution: the group's cost is shared evenly
        return test, train, t_prefix + t_group / n_members, 0.0, False

    # -- one cell --------------------------------------------------------
    def run(self, params, split_idx):
        est = self.estimator
        if isinstance(est, (Pipeline, FeatureUnion)):
            root = self._root_token(split_idx)
            root_pairwise = False
            if isinstance(est, Pipeline):
                first_real = next(
                    (s for _, s in est.steps
                     if not _is_dropped(s) and s != "passthrough"),
                    None,
                )
                root_pairwise = (
                    _is_pairwise(first_real) if first_real is not None else False
                )
            _tok, fitted, fit_time, _failed = self._fit_any(
                est, params, self._fit_params_for(split_idx), root, split_idx,
                root_pairwise,
            )
        else:
            fitted, fit_time = self._fit_plain(params, split_idx)

        pairwise = _is_pairwise(est)
        X_test = self.cv_cache.extract(split_idx, train=False, pairwise=pairwise)
        y_test = self.cv_cache.extract(split_idx, train=False, is_x=False)
        X_train = y_train = None
        if self.return_train_score:
            X_train = self.cv_cache.extract(split_idx, train=True,
                                            pairwise=pairwise)
            y_train = self.cv_cache.extract(split_idx, train=True, is_x=False)
        test, train, score_time = methods.score(
            fitted, X_test, y_test, X_train, y_train, self.scorers,
            self.error_score,
        )
        return test, train, fit_time, score_time, fitted is FIT_FAILURE


# ---------------------------------------------------------------------------
# batched-candidate planning
# ---------------------------------------------------------------------------


class _BatchGroup:
    """A bucket of homogeneous candidates fit+scored as one program."""

    __slots__ = ("members", "static", "token")

    def __init__(self, members, static, token):
        self.members = members  # list of varying-param dicts, one/member
        self.static = static  # terminal-stage overrides shared by the group
        self.token = token


def _plan_batched_groups(estimator, candidate_params, scorers, fit_params,
                         n_train_min=None):
    """→ ``{candidate_index: (_BatchGroup, member_idx)}`` for candidates
    eligible for the batched fast path (empty dict = everything runs the
    per-cell path).

    Eligibility: passthrough scoring only (the estimator's own ``score`` is
    what the batched program can compute in bulk; arbitrary scorer callables
    can't be batched), no fit_params, a terminal estimator declaring the
    protocol (``_batchable_params`` + ``_batched_fit_score``), candidates
    whose terminal params vary ONLY in batchable keys grouped by (prefix
    params, static terminal params), groups of ≥ 2. A candidate the
    estimator's ``_batchable_member_ok`` hook rejects (e.g. KMeans with
    ``n_clusters`` > the smallest train split) is EXCLUDED from its group
    and takes the per-cell path, so its individual failure follows
    error_score semantics instead of poisoning the whole group's program.
    """
    if fit_params:
        return {}
    if set(scorers) != {"score"} or scorers["score"] is not _passthrough_scorer:
        return {}
    if isinstance(estimator, Pipeline):
        if not estimator.steps:
            return {}
        term_name, term = estimator.steps[-1]
        if _is_dropped(term) or term == "passthrough" or isinstance(
                term, (Pipeline, FeatureUnion)):
            return {}
        prefix = term_name + "__"

        def split_params(p):
            tp, rest = {}, {}
            for k, v in p.items():
                if k.startswith(prefix):
                    tp[k[len(prefix):]] = v
                else:
                    rest[k] = v
            return tp, rest

    elif isinstance(estimator, FeatureUnion):
        return {}
    else:
        term = estimator

        def split_params(p):
            return dict(p), {}

    batchable = getattr(type(term), "_batchable_params", None)
    if not batchable or not hasattr(term, "_batched_fit_score"):
        return {}

    buckets: dict = {}
    for ci, p in enumerate(candidate_params):
        if isinstance(estimator, Pipeline) and any(
                "__" not in k for k in p):
            continue  # top-level overrides (steps=, stage replacement)
        tp, rest = split_params(p)
        varying = {k: v for k, v in tp.items() if k in batchable}
        static = {k: v for k, v in tp.items() if k not in batchable}
        merged = {**term.get_params(deep=False), **static}
        try:
            if not term._supports_batched(merged):
                continue
            member_ok = getattr(term, "_batchable_member_ok", None)
            if member_ok is not None and not member_ok(
                    {**merged, **varying}, n_train_min):
                continue
        except Exception:
            continue
        gk = tokenize("plan", sorted(rest.items()), sorted(static.items()))
        b = buckets.setdefault(gk, {"static": static, "members": [],
                                    "cis": []})
        b["members"].append(varying)
        b["cis"].append(ci)

    plan: dict = {}
    for b in buckets.values():
        if len(b["cis"]) < 2:
            continue
        grp = _BatchGroup(
            b["members"], b["static"],
            tokenize("members", b["members"], sorted(b["static"].items())),
        )
        for mi, ci in enumerate(b["cis"]):
            plan[ci] = (grp, mi)
    return plan


def _all_stages_device_native(estimator) -> bool:
    """True when the estimator (or every pipeline stage) is a dask_ml_tpu
    estimator — the condition under which the driver turns on
    ``device_outputs`` so stage outputs chain device→device."""
    def native(e):
        return type(e).__module__.startswith("dask_ml_tpu.")

    if isinstance(estimator, Pipeline):
        stages = [s for _, s in estimator.steps
                  if not _is_dropped(s) and s != "passthrough"]
        return bool(stages) and all(native(s) for s in stages)
    return native(estimator)


# ---------------------------------------------------------------------------
# the estimators
# ---------------------------------------------------------------------------


def _normalize_n_jobs(n_jobs):
    """-1 → one thread per host core (reference: _search.py:659-666)."""
    import os

    if n_jobs is None:
        return 1
    if n_jobs == -1:
        return os.cpu_count() or 1
    if n_jobs < 1:
        raise ValueError(f"n_jobs must be -1 or >= 1, got {n_jobs}")
    return int(n_jobs)


def _max_concurrent_device_jobs(n):
    """Cap a device-dispatching worker pool for the active backend.

    XLA:CPU's cross-module collectives (the psums every mesh-wide program
    carries) DEADLOCK when two programs execute concurrently over the same
    virtual device set: each launch's per-device participant threads
    rendezvous keyed by (device set, op id), and interleaved launches from
    a thread pool strand both runs waiting for the other's participants
    (observed as indefinite hangs of the cell pool on the 8-virtual-device
    test mesh; XLA logs "This thread has been waiting for 5000ms and may
    be stuck"). Real accelerator backends serialize launches on each
    device's stream, so the overlap this pool exists for — hiding the
    ~100 ms host↔device round-trip per cell — is both safe and profitable
    there. The cpu backend has no round-trip to hide, so concurrency buys
    nothing and only carries the hazard: cap the pool at one worker."""
    if n > 1:
        import jax

        if jax.default_backend() == "cpu":
            return 1
    return n


class TPUBaseSearchCV(BaseEstimator, MetaEstimatorMixin):
    """Shared driver for grid and randomized search
    (reference: _search.py:669-894 ``DaskBaseSearchCV``)."""

    def __init__(self, estimator, scoring=None, iid=True, refit=True, cv=None,
                 error_score="raise", return_train_score=True, scheduler=None,
                 n_jobs=-1, cache_cv=True, checkpoint=None,
                 cell_retries=0, cell_timeout=None):
        self.estimator = estimator
        self.scoring = scoring
        self.iid = iid
        self.refit = refit
        self.cv = cv
        self.error_score = error_score
        self.return_train_score = return_train_score
        # accepted for reference-signature parity; placement is the mesh's job
        self.scheduler = scheduler
        self.n_jobs = n_jobs
        self.cache_cv = cache_cv
        # path to an append-only cell journal; fit() resumes from it
        # (SURVEY §5.4 — capability-parity-plus over the reference)
        self.checkpoint = checkpoint
        # fault tolerance (docs/robustness.md): cell_retries re-attempts a
        # cell fit after a TRANSIENT failure (host I/O, device transfer —
        # parallel/faults.RetryPolicy classification) before the usual
        # error_score degradation; cell_timeout (seconds) is a SOFT per-cell
        # deadline — an overrunning cell scores error_score and the sweep
        # moves on, instead of one hung candidate poisoning the run
        self.cell_retries = cell_retries
        self.cell_timeout = cell_timeout

    def _get_param_iterator(self):  # pragma: no cover - abstract
        raise NotImplementedError

    # -- fit -------------------------------------------------------------
    def fit(self, X, y=None, groups=None, **fit_params):
        estimator = self.estimator
        if not (
            isinstance(self.error_score, numbers.Number)
            or self.error_score == "raise"
        ):
            raise ValueError(
                "error_score must be the string 'raise' or a numeric value"
            )
        scorers, multimetric = _resolve_scoring(estimator, self.scoring)
        refit_metric = self._check_refit(multimetric, scorers)

        # plain python sequences are legal inputs (sklearn's indexable()
        # contract); the split/slice machinery wants arrays
        if (X is not None and not hasattr(X, "shape")
                and not hasattr(X, "iloc") and not hasattr(X, "tocsr")):
            X = np.asarray(X)
        if (y is not None and not hasattr(y, "shape")
                and not hasattr(y, "iloc")):
            y = np.asarray(y)

        cv = check_cv(self.cv, y, classifier=is_classifier(estimator))
        splits = list(cv.split(X, y, groups))
        n_splits = len(splits)
        device_native = _all_stages_device_native(estimator)
        from dask_ml_tpu.parallel import shapes as shapes_lib

        cv_cache = CVCache(splits, X, y, cache=self.cache_cv,
                           device_slices=device_native,
                           pad_policy=shapes_lib.active_policy())

        candidate_params = list(self._get_param_iterator())
        n_candidates = len(candidate_params)

        if self.cell_timeout and device_native:
            import jax

            if jax.default_backend() == "cpu":
                import warnings

                warnings.warn(
                    "cell_timeout with jax-native estimators on the cpu "
                    "backend: a timed-out cell's stray thread keeps "
                    "dispatching mesh-wide programs, and XLA:CPU "
                    "cross-module collectives can interleave/deadlock with "
                    "subsequent cells (the same hazard "
                    "_max_concurrent_device_jobs caps the pool for). "
                    "Prefer cell_timeout for host-side estimators here; "
                    "accelerator backends serialize launches per device "
                    "stream and are safe.",
                    RuntimeWarning,
                )
        memo = _Memo()
        retry_policy = None
        if self.cell_retries:
            from dask_ml_tpu.parallel.faults import RetryPolicy

            retry_policy = RetryPolicy(max_retries=int(self.cell_retries))
        runner = _CandidateRunner(
            estimator, cv_cache, memo, scorers,
            self.error_score, self.return_train_score, fit_params=fit_params,
            retry_policy=retry_policy,
        )

        cells = [
            (ci, si)
            for ci in range(n_candidates)
            for si in range(n_splits)
        ]
        n_workers = _max_concurrent_device_jobs(
            _normalize_n_jobs(self.n_jobs))

        # Batched-candidate fast path: bucket homogeneous candidates and let
        # the terminal estimator fit+score each bucket as one compiled
        # program (see _plan_batched_groups). Unplanned candidates take the
        # per-cell path; both share the same prefix-fit memo tokens.
        batch_plan = _plan_batched_groups(
            estimator, candidate_params, scorers, fit_params,
            n_train_min=min((len(tr) for tr, _te in splits), default=None))

        # Checkpoint/resume: completed cells live in an append-only journal
        # keyed by content — estimator config + candidate params + the
        # split's ACTUAL index arrays + the CONTENT of X/y/fit_params +
        # scoring identity — so a re-fit with the same checkpoint path restores
        # finished cells and computes only the rest, while any change to
        # grid, data values, sample weights, or scoring changes the keys and
        # naturally misses. Cells that FAILED under a numeric error_score
        # are never journaled: an interrupted run's transient failures (OOM,
        # preemption) retry on resume instead of being restored as scores.
        # (SURVEY §5.4; the reference can only re-run from zero.)
        journal = done_cells = None
        cell_keys = {}
        legacy_keys = {}
        if self.checkpoint:
            from dask_ml_tpu.checkpoint import CellJournal

            journal = CellJournal(self.checkpoint)
            done_cells = journal.load()
            est_token = tokenize(
                type(estimator), estimator.get_params(deep=True),
                _content_array(X), _content_array(y),
                {k: _content_array(v) for k, v in fit_params.items()},
            )
            scoring_id = _scoring_identity(self.scoring)
            # Journals written before scoring identity keyed cells on scorer
            # NAMES (sorted(scorers)). Probe the legacy key on a miss ONLY
            # for list-of-strings specs, where the names that reached the
            # legacy key ARE the metrics. Everything else is ambiguous in
            # legacy keys: None/single-string collapsed to ['score'], and a
            # dict's keys are arbitrary slot names whose mapped metric could
            # have changed — a legacy record can't prove WHICH metric
            # produced it. Callable scoring's legacy records are exactly the
            # stale ones the identity change invalidates. No journal loaded
            # → nothing to bridge, skip the second hashing pass entirely.
            named_scoring = (
                isinstance(self.scoring, (list, tuple, set))
                and all(isinstance(s, str) for s in self.scoring)
            )
            for ci, si in cells:
                cell_keys[(ci, si)] = tokenize(
                    "cell", est_token, candidate_params[ci],
                    splits[si][0], splits[si][1], scoring_id,
                    self.return_train_score,
                )
                if named_scoring and done_cells:
                    legacy_keys[(ci, si)] = tokenize(
                        "cell", est_token, candidate_params[ci],
                        splits[si][0], splits[si][1], sorted(scorers),
                        self.return_train_score,
                    )
        self.n_resumed_cells_ = sum(
            1 for cs, k in cell_keys.items()
            if k in (done_cells or {})
            or (cs in legacy_keys and legacy_keys[cs] in (done_cells or {}))
        )

        # Thread-local config (dtype etc.) set on the CALLING thread must
        # reach the pool's worker threads, or `config_context(dtype=bf16):
        # search.fit(...)` would silently stage f32 under n_jobs > 1. The
        # mesh knob is excluded: mesh scoping is already process-visible
        # (and re-pushing it per worker would race on the mesh stack).
        from dask_ml_tpu import config as config_lib

        # mesh is excluded because mesh scoping is process-visible already;
        # compilation_cache because it is a process-wide jax setting that
        # config_context rejects by design
        caller_cfg = {
            k: v for k, v in config_lib.get_config().items()
            if k not in ("mesh", "compilation_cache")
        }
        if device_native:
            # all-jax-native candidate pipelines: stage outputs flow
            # device→device between pipeline steps for the whole search
            # (over a slow host link, per-stage fetch+restage dominates) —
            # scoped to the cells, so refit and the returned estimator keep
            # the numpy sklearn contract
            caller_cfg["device_outputs"] = True

        def _compute_cell(ci, si):
            if ci in batch_plan:
                group, mi = batch_plan[ci]
                return runner.run_batched(candidate_params[ci], si, group, mi)
            return runner.run(candidate_params[ci], si)

        # Soft per-cell timeout: the cell runs on a dedicated daemon thread
        # and the worker waits at most cell_timeout seconds. A cell that
        # overruns scores error_score (never journaled, so a resume retries
        # it) and the sweep proceeds — threads cannot be killed, so the
        # stray fit finishes in the background, but it no longer blocks the
        # run or poisons its results. "Soft" is the honest contract here.
        timeout_counts = [0]
        timeout_lock = threading.Lock()

        def _timed_out_result(ci, si):
            if self.error_score == "raise":
                raise TimeoutError(
                    f"search cell (candidate {ci}, split {si}) exceeded "
                    f"cell_timeout={self.cell_timeout}s")
            methods.warn_fit_failure(
                self.error_score,
                TimeoutError(f"cell exceeded cell_timeout="
                             f"{self.cell_timeout}s"))
            test, train, score_time = methods.score(
                FIT_FAILURE, None, None,
                FIT_FAILURE if self.return_train_score else None,
                None, scorers, self.error_score)
            return test, train, float(self.cell_timeout), score_time, True

        def _compute_cell_deadline(ci, si):
            with telemetry.span("search.cell", candidate=int(ci),
                                split=int(si)):
                return _compute_cell_deadline_inner(ci, si)

        def _compute_cell_deadline_inner(ci, si):
            value, timed_out = run_with_soft_deadline(
                lambda: _compute_cell(ci, si), self.cell_timeout,
                caller_cfg=caller_cfg, name=f"search-cell-{ci}-{si}")
            if timed_out:
                with timeout_lock:
                    timeout_counts[0] += 1
                # registry mirror of the timeout count surfaced as
                # n_cell_timeouts_ (same increment site)
                telemetry.counter("search.cell_timeouts").inc()
                return _timed_out_result(ci, si)
            return value

        def run_cell(ci, si):
            with config_lib.config_context(**caller_cfg):
                if journal is not None:
                    key = cell_keys[(ci, si)]
                    hit = done_cells.get(key)
                    if hit is None and (ci, si) in legacy_keys:
                        hit = done_cells.get(legacy_keys[(ci, si)])
                        if hit is not None:  # migrate to the current key
                            journal.append(key, hit)
                    if hit is not None:
                        return hit
                    result = _compute_cell_deadline(ci, si)
                    if not result[-1]:  # journal only non-failed cells
                        journal.append(key, result)
                    return result
                return _compute_cell_deadline(ci, si)

        # Device-staging memo: jax-native candidates re-stage their CV slice
        # inside fit; within this scope identical (slice, role) pairs upload
        # once for the whole search (the analogue of the reference's
        # data-key sharing, model_selection/utils.py:53-68).
        from dask_ml_tpu.parallel.sharding import staging_memo

        with staging_memo() as dmemo:
            # Pre-pass for batched groups: dispatch every group's program
            # (prefix fits + the batched fit+score are pure async dispatch
            # under device_outputs) and bulk-fetch ALL outputs in one
            # device sync — per-group fetches each cost ~2 RTT and
            # serialize on a tunneled host link, which dominated the sweep.
            if batch_plan:
                group_cis: dict = {}
                for ci, (group, _mi) in batch_plan.items():
                    group_cis.setdefault(id(group), (group, []))[1].append(ci)
                def _cell_journaled(cj, si):
                    if cell_keys[(cj, si)] in done_cells:
                        return True
                    lk = legacy_keys.get((cj, si))
                    return lk is not None and lk in done_cells

                jobs = [
                    (group, cis,
                     [si for si in range(n_splits)
                      if journal is None or not all(
                          _cell_journaled(cj, si) for cj in cis)])
                    for group, cis in group_cis.values()
                ]
                jobs = [j for j in jobs if j[2]]

                def _dispatch_group(job, only_first=False):
                    group, cis, sis = job
                    out = []
                    # config is thread-local: re-enter it per worker
                    with config_lib.config_context(**caller_cfg):
                        for si in (sis[:1] if only_first else sis):
                            res, _tp = runner.batched_group_out(
                                candidate_params[cis[0]], si, group)
                            out.append(
                                res[0] if isinstance(res, tuple) else None)
                    return out

                # Cold-start structure (VERDICT r4 #2), exploiting two
                # facts: XLA compiles release the GIL (distinct programs
                # CAN build concurrently), but jax has no in-flight
                # compile dedup (two threads first-calling the same
                # program both pay the full compile). So: (1) one
                # serial warm-up job compiles everything the groups
                # share — staging, prefix-fit, and (shape-bucketed)
                # group programs; (2) the remaining groups then fan out
                # on a pool, overlapping whatever group-specific
                # compiles survive the bucketing, each program built
                # exactly once. A group's splits run inside one job
                # (same programs — racing them across workers would
                # duplicate every compile). The memo/CVCache are
                # lock-protected (the n_jobs>1 cell pool already drives
                # them concurrently); the peek scope is entered once
                # here, on this thread, before the workers start.
                with memo.peek_scope():
                    head = (_dispatch_group(jobs[0], only_first=True)
                            if jobs else [])
                    rests = ([(jobs[0][0], jobs[0][1], jobs[0][2][1:])]
                             if jobs else [])
                    rests += jobs[1:]
                    rests = [j for j in rests if j[2]]
                    if len(rests) <= 1:
                        tails = [_dispatch_group(j) for j in rests]
                    else:
                        with ThreadPoolExecutor(
                            max_workers=_max_concurrent_device_jobs(
                                min(8, len(rests)))
                        ) as pre_pool:
                            tails = list(
                                pre_pool.map(_dispatch_group, rests))
                pending = [p for chunk in [head] + tails for p in chunk
                           if p is not None]
                if pending:
                    import jax

                    host = jax.device_get([o["scores"] for o in pending])
                    for o, hs in zip(pending, host):
                        o["scores"] = list(hs)

            if n_workers == 1:
                results = [run_cell(ci, si) for ci, si in cells]
            else:
                with ThreadPoolExecutor(max_workers=n_workers) as pool:
                    futs = [
                        pool.submit(run_cell, ci, si) for ci, si in cells
                    ]
                    results = [f.result() for f in futs]
        self.n_device_stagings_ = dmemo.n_stagings
        self.n_staging_hits_ = dmemo.hits
        results = [r[:4] for r in results]  # drop the cell failure flag

        test_weights = None
        if self.iid:
            test_weights = np.array(
                [cv_cache.n_test(si) for _, si in cells], dtype=np.float64
            )

        self.cv_results_ = methods.create_cv_results(
            results, candidate_params, n_splits, self.error_score,
            test_weights, multimetric, self.return_train_score,
        )
        self.n_splits_ = n_splits
        self.multimetric_ = multimetric
        self.scorer_ = scorers if multimetric else scorers["score"]
        self.n_shared_fits_ = memo.n_entries  # CSE observability
        # shape-bucket observability: the padded sample counts this
        # search's fold slices shared (compile counts scale with THIS, not
        # with candidates × folds — see CVCache.planned_buckets)
        self.shape_buckets_ = cv_cache.planned_buckets()
        # cells that ACTUALLY read a batched group's result this fit —
        # runtime declines (NotImplemented) and journal-resumed cells are
        # excluded, so the attribute is evidence of which path ran
        self.n_batched_cells_ = runner.n_batched_done
        self._shared_fit_graph = memo.report()
        # fault-tolerance observability: transient retries spent on cell
        # fits and cells cut off by the soft timeout, surfaced both as
        # attributes and in shared_fit_report()
        self.n_cell_retries_ = (retry_policy.retries
                                if retry_policy is not None else 0)
        self.n_cell_timeouts_ = timeout_counts[0]
        self.retry_stats_ = (retry_policy.stats()
                             if retry_policy is not None else None)

        # best_* availability follows sklearn: single-metric scoring gets
        # best_index_/best_score_/best_params_ even with refit=False;
        # multimetric needs refit=<metric name> to define "best"
        if self.refit or not multimetric:
            rank_key = (
                f"rank_test_{refit_metric}" if multimetric else "rank_test_score"
            )
            self.best_index_ = int(np.argmin(self.cv_results_[rank_key]))
            mean_key = (
                f"mean_test_{refit_metric}" if multimetric else "mean_test_score"
            )
            self.best_score_ = float(
                self.cv_results_[mean_key][self.best_index_]
            )
            self.best_params_ = candidate_params[self.best_index_]
        if self.refit:
            # refit always raises on failure (reference: _search.py:965-969)
            best = methods.copy_estimator(estimator)
            best.set_params(**self.best_params_)
            best.fit(X, y, **fit_params)
            self.best_estimator_ = best
        return self

    def _check_refit(self, multimetric, scorers):
        if not multimetric:
            return None
        if self.refit is False:
            return None
        if not isinstance(self.refit, str) or self.refit not in scorers:
            raise ValueError(
                "For multimetric scoring, refit must be the name of the "
                f"scorer used to find the best parameters; got {self.refit!r}"
            )
        return self.refit

    # -- search introspection (reference: _search.py:870-894) ------------

    def shared_fit_report(self) -> str:
        """Human-readable view of the work-sharing (CSE) DAG: every
        memoized node with how many cells consumed it, ordered by sharing.

        The reference's ``visualize()`` renders the merged dask graph to
        show that pipeline-prefix fits are shared (reference:
        _search.py:870-894, docs/source/hyper-parameter-search.rst:78-135);
        this is the same evidence as text — each node ran its computation
        ONCE however many consumers it lists.
        """
        if not hasattr(self, "_shared_fit_graph"):
            raise AttributeError("Not fitted; call fit first")
        nodes = self._shared_fit_graph
        header = (f"{len(nodes)} distinct computations served "
                  f"{sum(m['consumers'] for m in nodes.values())} consumers")
        retries = getattr(self, "n_cell_retries_", 0)
        timeouts = getattr(self, "n_cell_timeouts_", 0)
        if retries or timeouts:
            header += (f"; {retries} transient fit retr"
                       f"{'y' if retries == 1 else 'ies'}, "
                       f"{timeouts} timed-out cell"
                       f"{'' if timeouts == 1 else 's'}")
        lines = [
            header,
            "",
            f"{'consumers':>9}  {'node':<40} key",
        ]
        order = sorted(nodes.items(),
                       key=lambda kv: -kv[1]["consumers"])
        for key, m in order:
            label = m["label"] or "(input)"
            lines.append(f"{m['consumers']:>9}  {label:<40} {key[:12]}")
        # unified-telemetry view (docs/observability.md): the same
        # spans/metrics/compile rollup telemetry_report() exports as a
        # dict. Shown when the knob is on OR when spans were recorded —
        # a fit run under config_context(telemetry=True) keeps its
        # telemetry section even when the report is read outside that
        # scope.
        if telemetry.enabled() or telemetry.spans():
            lines += ["", telemetry.render_report()]
        return "\n".join(lines)

    def visualize(self, filename: Optional[str] = "mydask",
                  format: Optional[str] = None, **kwargs):
        """Render the shared-fit DAG with graphviz (parity with the
        reference's ``DaskBaseSearchCV.visualize``, _search.py:870-894 —
        same ``(filename, format=None, **kwargs)`` surface, defaulting to
        png). Requires the optional ``graphviz`` package; use
        :meth:`shared_fit_report` for the dependency-free text view."""
        if not hasattr(self, "_shared_fit_graph"):
            raise AttributeError("Not fitted; call fit first")
        try:
            import graphviz
        except ImportError as e:  # pragma: no cover - optional dep
            raise ImportError(
                "visualize() needs the optional 'graphviz' package; "
                "shared_fit_report() provides the same information as text"
            ) from e
        g = graphviz.Digraph("shared_fits")
        nodes = self._shared_fit_graph
        for key, m in nodes.items():
            label = m["label"] or "input"
            g.node(key[:12], f"{label}\\n×{m['consumers']}")
        for key, m in nodes.items():
            for p in m["parents"]:
                if p in nodes:
                    g.edge(p[:12], key[:12])
        if filename:
            g.render(filename, format=format or "png", cleanup=True,
                     **kwargs)
        return g

    # -- post-fit delegation (reference: _search.py:728-762) -------------
    def _check_is_fitted(self, method_name):
        if not self.refit:
            raise AttributeError(
                f"This {type(self).__name__} instance was initialized with "
                f"refit=False; {method_name} is only available after refitting"
            )
        if not hasattr(self, "best_estimator_"):
            raise AttributeError("Not fitted; call fit first")

    @property
    def classes_(self):
        self._check_is_fitted("classes_")
        return self.best_estimator_.classes_

    def predict(self, X):
        self._check_is_fitted("predict")
        return self.best_estimator_.predict(X)

    def predict_proba(self, X):
        self._check_is_fitted("predict_proba")
        return self.best_estimator_.predict_proba(X)

    def predict_log_proba(self, X):
        self._check_is_fitted("predict_log_proba")
        return self.best_estimator_.predict_log_proba(X)

    def decision_function(self, X):
        self._check_is_fitted("decision_function")
        return self.best_estimator_.decision_function(X)

    def transform(self, X):
        self._check_is_fitted("transform")
        return self.best_estimator_.transform(X)

    def inverse_transform(self, X):
        self._check_is_fitted("inverse_transform")
        return self.best_estimator_.inverse_transform(X)

    def score(self, X, y=None):
        self._check_is_fitted("score")
        if self.multimetric_:
            # score with the refit metric, as sklearn's BaseSearchCV does
            if isinstance(self.refit, str):
                return self.scorer_[self.refit](self.best_estimator_, X, y)
            return self.best_estimator_.score(X, y)
        return self.scorer_(self.best_estimator_, X, y)


_DOC_NOTE = """
    Execution model: a host-side thread pool drives candidate x split fits;
    pipeline-prefix fits are content-addressed and computed once across
    candidates (work-sharing), the analogue of the reference's graph CSE
    (reference: _search.py:281-345,462-503). `n_shared_fits_` exposes how many
    distinct fit tasks actually ran.
"""


class GridSearchCV(TPUBaseSearchCV):
    __doc__ = (
        "Exhaustive search over a parameter grid "
        "(reference: _search.py:1141-1170).\n" + _DOC_NOTE
    )

    def __init__(self, estimator, param_grid, scoring=None, iid=True,
                 refit=True, cv=None, error_score="raise",
                 return_train_score=True, scheduler=None, n_jobs=-1,
                 cache_cv=True, checkpoint=None, cell_retries=0,
                 cell_timeout=None):
        super().__init__(
            estimator, scoring=scoring, iid=iid, refit=refit, cv=cv,
            error_score=error_score, return_train_score=return_train_score,
            scheduler=scheduler, n_jobs=n_jobs, cache_cv=cache_cv,
            checkpoint=checkpoint, cell_retries=cell_retries,
            cell_timeout=cell_timeout,
        )
        self.param_grid = param_grid

    def _get_param_iterator(self):
        return ParameterGrid(self.param_grid)


class RandomizedSearchCV(TPUBaseSearchCV):
    __doc__ = (
        "Sampled search over parameter distributions "
        "(reference: _search.py:1232-1265).\n" + _DOC_NOTE
    )

    def __init__(self, estimator, param_distributions, n_iter=10, scoring=None,
                 iid=True, refit=True, cv=None, random_state=None,
                 error_score="raise", return_train_score=True, scheduler=None,
                 n_jobs=-1, cache_cv=True, checkpoint=None, cell_retries=0,
                 cell_timeout=None):
        super().__init__(
            estimator, scoring=scoring, iid=iid, refit=refit, cv=cv,
            error_score=error_score, return_train_score=return_train_score,
            scheduler=scheduler, n_jobs=n_jobs, cache_cv=cache_cv,
            checkpoint=checkpoint, cell_retries=cell_retries,
            cell_timeout=cell_timeout,
        )
        self.param_distributions = param_distributions
        self.n_iter = n_iter
        self.random_state = random_state

    def _get_param_iterator(self):
        return ParameterSampler(
            self.param_distributions, self.n_iter,
            random_state=self.random_state,
        )
