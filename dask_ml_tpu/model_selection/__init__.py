"""Hyper-parameter search and CV splitting
(reference: dask_ml/model_selection/__init__.py)."""

from dask_ml_tpu.model_selection._incremental import (
    HyperbandSearchCV,
    SuccessiveHalvingSearchCV,
)
from dask_ml_tpu.model_selection._search import (
    GridSearchCV,
    RandomizedSearchCV,
    TPUBaseSearchCV,
)
from dask_ml_tpu.model_selection._split import (
    KFold,
    ShuffleSplit,
    check_cv,
    compute_n_splits,
    train_test_split,
)

__all__ = [
    "GridSearchCV",
    "HyperbandSearchCV",
    "RandomizedSearchCV",
    "SuccessiveHalvingSearchCV",
    "TPUBaseSearchCV",
    "KFold",
    "ShuffleSplit",
    "check_cv",
    "compute_n_splits",
    "train_test_split",
]
