"""Cross-validation splitting for sharded sample-axis data.

TPU-native rebuild of the reference's blockwise splitters
(reference: model_selection/_split.py). The reference splits each dask chunk
*locally* — per-chunk seeded permutations, offset-concatenated into global
index arrays — so a split never moves rows between workers
(reference: _split.py:144-173 ``_generate_idx``/offset logic). We keep exactly
that algorithm, with "chunk" = "mesh data shard": indices are generated
per block with per-block seeds and offset into global row ids, so the train
and test selections of every split stay shard-local under the data-axis
sharding and the later gather is a shard-local ``jnp.take``.

Index generation happens on the host (it is O(n) integer work and happens once
per search); the expensive part — slicing X rows and staging them onto the
mesh — is done by the consumer (`train_test_split` here, or the search driver)
per split.
"""

from __future__ import annotations

import numbers
from typing import Optional

import numpy as np
import sklearn.model_selection as sk_ms
from sklearn.model_selection._split import BaseCrossValidator

from dask_ml_tpu.parallel import mesh as mesh_lib


def _check_blockwise_sizes(test_size, train_size):
    """The reference restricts blockwise splits to float fractions
    (reference: _split.py:27-55): integer sizes cannot be honored exactly when
    each block is split locally."""
    if test_size is None and train_size is None:
        test_size = 0.1
    for name, value in (("test_size", test_size), ("train_size", train_size)):
        if value is not None and not isinstance(value, numbers.Real):
            raise ValueError(f"{name} must be a float fraction, got {value!r}")
        if value is not None and isinstance(value, numbers.Integral):
            raise ValueError(
                f"{name} must be a float fraction for blockwise splits "
                f"(reference restriction, _split.py:27-55); got int {value!r}"
            )
        if value is not None and not 0 < value < 1:
            raise ValueError(f"{name} must be in (0, 1), got {value!r}")
    if test_size is None:
        test_size = 1.0 - train_size
    if train_size is None:
        train_size = 1.0 - test_size
    if test_size + train_size > 1 + 1e-9:
        raise ValueError(
            f"test_size + train_size = {test_size + train_size} > 1"
        )
    return float(test_size), float(train_size)


def _block_sizes(n: int, n_blocks: int) -> list[int]:
    """Split ``n`` rows into ``n_blocks`` near-equal contiguous blocks — the
    analogue of the dataset's shard layout (ceil-sized shards then remainder,
    matching the padded-shard row distribution)."""
    n_blocks = max(1, min(n_blocks, n))
    base, extra = divmod(n, n_blocks)
    return [base + (1 if i < extra else 0) for i in range(n_blocks)]


def _generate_idx(n: int, seed: int, n_train: int, n_test: int):
    """Permute ``arange(n)``; first ``n_train`` are train, last ``n_test`` are
    test — same per-block scheme as the reference (_split.py:144-160)."""
    idx = np.random.RandomState(seed).permutation(n)
    return idx[:n_train], idx[n - n_test:]


class ShuffleSplit(BaseCrossValidator):
    """Random-permutation CV that splits each data block locally
    (reference: model_selection/_split.py:82-180).

    Parameters
    ----------
    n_splits : int, default 10
    test_size, train_size : float fractions (blockwise restriction, as in the
        reference)
    blockwise : bool, default True
        Permute within blocks (shard-local, no cross-shard data motion). The
        reference raises NotImplementedError for ``blockwise=False``
        (_split.py:175-177); we implement it as a global permutation since on
        host index arrays it is trivial.
    n_blocks : int or None
        Number of blocks; default = the active mesh's data-shard count.
    random_state : int or None
    """

    def __init__(
        self,
        n_splits: int = 10,
        test_size=None,
        train_size=None,
        blockwise: bool = True,
        n_blocks: Optional[int] = None,
        random_state=None,
    ):
        self.n_splits = n_splits
        self.test_size = test_size
        self.train_size = train_size
        self.blockwise = blockwise
        self.n_blocks = n_blocks
        self.random_state = random_state

    def get_n_splits(self, X=None, y=None, groups=None):
        return self.n_splits

    def _iter_test_masks(self, X=None, y=None, groups=None):  # pragma: no cover
        raise NotImplementedError  # split() is overridden wholesale

    def split(self, X, y=None, groups=None):
        n = int(X.shape[0])
        test_size, train_size = _check_blockwise_sizes(
            self.test_size, self.train_size
        )
        rng = np.random.RandomState(self.random_state)
        for _ in range(self.n_splits):
            if self.blockwise:
                yield self._split_blockwise(n, test_size, train_size, rng)
            else:
                yield self._split_global(n, test_size, train_size, rng)

    def _split_blockwise(self, n, test_size, train_size, rng):
        n_blocks = self.n_blocks or mesh_lib.n_data_shards()
        sizes = _block_sizes(n, n_blocks)
        seeds = rng.randint(0, 2**31 - 1, size=len(sizes))
        trains, tests = [], []
        offset = 0
        for size, seed in zip(sizes, seeds):
            n_test = int(size * test_size)
            n_train = int(size * train_size)
            tr, te = _generate_idx(size, int(seed), n_train, n_test)
            trains.append(offset + np.sort(tr))
            tests.append(offset + np.sort(te))
            offset += size
        return np.concatenate(trains), np.concatenate(tests)

    def _split_global(self, n, test_size, train_size, rng):
        n_test = int(n * test_size)
        n_train = int(n * train_size)
        tr, te = _generate_idx(n, int(rng.randint(0, 2**31 - 1)), n_train, n_test)
        return np.sort(tr), np.sort(te)


class KFold(BaseCrossValidator):
    """K contiguous folds over the sample axis.

    Contiguous (unshuffled) folds keep every fold's rows contiguous in the
    shard layout, so the train/test gathers of a split touch at most
    ``ceil(S/k)+1`` shard boundaries. With ``shuffle=True`` row order is
    permuted globally first (host index work only).
    """

    def __init__(self, n_splits: int = 5, shuffle: bool = False, random_state=None):
        self.n_splits = n_splits
        self.shuffle = shuffle
        self.random_state = random_state

    def get_n_splits(self, X=None, y=None, groups=None):
        return self.n_splits

    def _iter_test_masks(self, X=None, y=None, groups=None):  # pragma: no cover
        raise NotImplementedError

    def split(self, X, y=None, groups=None):
        n = int(X.shape[0])
        if self.n_splits < 2:
            raise ValueError("n_splits must be >= 2")
        if self.n_splits > n:
            raise ValueError(
                f"n_splits={self.n_splits} greater than n_samples={n}"
            )
        if self.shuffle:
            order = np.random.RandomState(self.random_state).permutation(n)
        else:
            order = np.arange(n)
        sizes = _block_sizes(n, self.n_splits)
        offset = 0
        for size in sizes:
            test = order[offset:offset + size]
            train = np.concatenate([order[:offset], order[offset + size:]])
            yield np.sort(train), np.sort(test)
            offset += size


def check_cv(cv=None, y=None, classifier: bool = False):
    """Resolve ``cv`` into a splitter object (reference: _search.py:600-618).

    int/None → our :class:`KFold`, or sklearn ``StratifiedKFold`` when
    ``classifier`` and ``y`` looks categorical (binary/multiclass) — the same
    dispatch rule as sklearn/the reference; splitter instances pass through.
    """
    if cv is None:
        cv = 5
    if isinstance(cv, numbers.Integral):
        if classifier and y is not None:
            from sklearn.utils.multiclass import type_of_target

            if type_of_target(np.asarray(y)) in ("binary", "multiclass"):
                return sk_ms.StratifiedKFold(n_splits=int(cv))
        return KFold(n_splits=int(cv))
    if hasattr(cv, "split") and hasattr(cv, "get_n_splits"):
        return cv
    if hasattr(cv, "__iter__"):
        # explicit (train_idx, test_idx) pairs, as sklearn accepts
        return sk_ms.check_cv(list(cv))
    raise ValueError(f"Cannot interpret cv={cv!r}")


def compute_n_splits(cv, X=None, y=None, groups=None) -> int:
    """Number of splits (reference: _search.py:621-656 avoids materializing
    lazy inputs; here inputs are host arrays so this is a plain delegation)."""
    return cv.get_n_splits(X, y, groups)


def train_test_split(
    *arrays,
    test_size=None,
    train_size=None,
    random_state=None,
    shuffle: bool = True,
    blockwise: bool = True,
    **options,
):
    """Split arrays into random train and test subsets
    (reference: model_selection/_split.py:220-289).

    All arrays must share axis-0 length. Index generation is blockwise (see
    :class:`ShuffleSplit`); slicing happens on the host and the caller stages
    the result onto the mesh (estimators do this internally).
    """
    if not arrays:
        raise ValueError("At least one array required as input")
    if options:
        raise TypeError(f"Unexpected options {sorted(options)}")
    if not shuffle:
        raise NotImplementedError(
            "shuffle=False is not implemented (the reference has the same "
            "restriction, _split.py:248-251)"
        )
    n = arrays[0].shape[0]
    for a in arrays:
        if a.shape[0] != n:
            raise ValueError(
                f"Input arrays have inconsistent lengths: {a.shape[0]} != {n}"
            )
    splitter = ShuffleSplit(
        n_splits=1,
        test_size=test_size,
        train_size=train_size,
        blockwise=blockwise,
        random_state=random_state,
    )
    train_idx, test_idx = next(splitter.split(arrays[0]))
    out = []
    for a in arrays:
        # keep pandas objects intact (positional slicing), arrays as arrays
        if hasattr(a, "iloc"):
            out.append(a.iloc[train_idx])
            out.append(a.iloc[test_idx])
        else:
            a = np.asarray(a)
            out.append(a[train_idx])
            out.append(a[test_idx])
    return out
