"""Task bodies for the hyper-parameter search driver.

The reference stores these callables inside hand-built dask graph tuples
(reference: model_selection/methods.py). Here they are invoked by the
host-side thread-pool driver in :mod:`._search`; the semantics carried over
verbatim are the ones the reference's test-suite pins down:

- ``FIT_FAILURE`` sentinel + ``error_score`` handling: any exception inside a
  fit is caught, warned as ``FitFailedWarning``, and propagated as a sentinel
  that scoring converts into the numeric ``error_score``
  (reference: methods.py:50-59, 194-249).
- per-task fit/score wall-times surfaced into ``cv_results_``
  (reference: methods.py:213-224, 261-269 → :338-339).
- ``create_cv_results``: sklearn-compatible results dict with masked param
  arrays, mean/std over splits, optional iid weighting, and min-rank
  tie-breaking (reference: methods.py:286-368).

Estimator copying uses ``copy.deepcopy`` — the same choice the reference makes
because ``sklearn.clone`` is not thread-safe (reference:
model_selection/utils.py:71-76); our driver is threaded too.
"""

from __future__ import annotations

import copy
import warnings
from timeit import default_timer

import numpy as np
from scipy.stats import rankdata
from sklearn.exceptions import FitFailedWarning


class FitFailure:
    """Singleton marking a failed fit (reference: methods.py:50-53)."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "FIT_FAILURE"


FIT_FAILURE = FitFailure()


def warn_fit_failure(error_score, exc):
    warnings.warn(
        "Classifier fit failed. The score on this train-test partition for "
        f"these parameters will be set to {error_score}. Details:\n{exc!r}",
        FitFailedWarning,
    )


def copy_estimator(est):
    """Thread-safe estimator copy (reference: model_selection/utils.py:71-76)."""
    return copy.deepcopy(est)


def set_params(est, params):
    est.set_params(**params)
    return est


def _run_attempts(attempt, retry_policy, label):
    """Run one fit attempt, optionally under a transient-error retry policy
    (each attempt starts from a FRESH estimator copy inside ``attempt``, so
    a partially-fitted failure never leaks into the retry). Non-transient
    errors propagate immediately and fall into ``error_score`` handling
    exactly as before."""
    if retry_policy is None:
        return attempt()
    return retry_policy.run(attempt, kind="search-fit", detail=label)


def fit(est, X, y, params=None, fit_params=None, error_score="raise",
        retry_policy=None):
    """Fit a (copied) estimator; returns ``(fitted_or_FIT_FAILURE, fit_time)``
    (reference: methods.py:194-224). ``retry_policy`` retries transient
    failures (host I/O, device transfer) before the ``error_score``
    degradation applies."""
    start = default_timer()

    def attempt():
        e2 = copy_estimator(est)
        if params:
            set_params(e2, params)
        if X is FIT_FAILURE:
            raise ValueError("Upstream pipeline stage failed to fit")
        e2.fit(X, y, **(fit_params or {}))
        return e2

    try:
        est = _run_attempts(attempt, retry_policy, type(est).__name__)
    except Exception as e:
        if error_score == "raise":
            raise
        warn_fit_failure(error_score, e)
        est = FIT_FAILURE
    return est, default_timer() - start


def fit_transform(est, X, y, params=None, fit_params=None, error_score="raise",
                  retry_policy=None):
    """Fit+transform for pipeline stages; returns
    ``((fitted, Xt) | (FIT_FAILURE, FIT_FAILURE), fit_time)``
    (reference: methods.py:227-249)."""
    start = default_timer()

    def attempt():
        e2 = copy_estimator(est)
        if params:
            set_params(e2, params)
        if X is FIT_FAILURE:
            raise ValueError("Upstream pipeline stage failed to fit")
        if hasattr(e2, "fit_transform"):
            Xt = e2.fit_transform(X, y, **(fit_params or {}))
        else:
            e2.fit(X, y, **(fit_params or {}))
            Xt = e2.transform(X)
        return e2, Xt

    try:
        est, Xt = _run_attempts(attempt, retry_policy, type(est).__name__)
    except Exception as e:
        if error_score == "raise":
            raise
        warn_fit_failure(error_score, e)
        est = FIT_FAILURE
        Xt = FIT_FAILURE
    return (est, Xt), default_timer() - start


def score(est, X_test, y_test, X_train, y_train, scorers, error_score):
    """Score a fitted estimator; ``scorers`` is ``{name: scorer}`` or a single
    callable under the key ``"score"``. Returns
    ``(test_scores, train_scores_or_None, score_time)``
    (reference: methods.py:252-269).
    """
    start = default_timer()
    if est is FIT_FAILURE:
        if error_score == "raise":  # pragma: no cover - guarded upstream
            raise ValueError("Fit failed with error_score='raise'")
        test = {name: float(error_score) for name in scorers}
        train = {name: float(error_score) for name in scorers}
    else:
        test = {name: float(s(est, X_test, y_test)) for name, s in scorers.items()}
        train = None
        if X_train is not None:
            train = {
                name: float(s(est, X_train, y_train))
                for name, s in scorers.items()
            }
    if X_train is None:
        train = None
    return test, train, default_timer() - start


MISSING = type("MissingParameter", (), {"__repr__": lambda s: "MISSING"})()


def create_cv_results(
    scores,
    candidate_params,
    n_splits: int,
    error_score,
    test_weights,
    multimetric: bool,
    return_train_score: bool,
):
    """Assemble the sklearn-compatible ``cv_results_`` dict
    (reference: methods.py:286-368).

    ``scores`` is a list (one entry per candidate×split, candidate-major) of
    ``(test_scores: dict, train_scores: dict|None, fit_time, score_time)``.
    ``test_weights`` (iid weighting) is an (n_candidates, n_splits) array of
    test-set sizes or None.
    """
    n_candidates = len(candidate_params)
    assert len(scores) == n_candidates * n_splits

    fit_times = np.array([s[2] for s in scores]).reshape(n_candidates, n_splits)
    score_times = np.array([s[3] for s in scores]).reshape(n_candidates, n_splits)

    results = {
        "mean_fit_time": fit_times.mean(axis=1),
        "std_fit_time": fit_times.std(axis=1),
        "mean_score_time": score_times.mean(axis=1),
        "std_score_time": score_times.std(axis=1),
        "params": candidate_params,
    }

    # param_<name> masked arrays (MISSING where a candidate lacks the key)
    keys = sorted({k for p in candidate_params for k in p})
    for key in keys:
        values = [p.get(key, MISSING) for p in candidate_params]
        mask = [v is MISSING for v in values]
        results[f"param_{key}"] = np.ma.MaskedArray(
            np.array(values, dtype=object), mask=mask
        )

    metric_names = sorted(scores[0][0]) if scores else ["score"]

    def _store(name_suffix, table, weights=None, rank=False):
        results.update(
            {
                f"split{i}_{name_suffix}": table[:, i]
                for i in range(n_splits)
            }
        )
        if weights is not None:
            mean = np.average(table, axis=1, weights=weights)
        else:
            mean = table.mean(axis=1)
        results[f"mean_{name_suffix}"] = mean
        # weighted std about the (possibly weighted) mean, as sklearn does
        if weights is not None:
            std = np.sqrt(
                np.average((table - mean[:, None]) ** 2, axis=1, weights=weights)
            )
        else:
            std = table.std(axis=1)
        results[f"std_{name_suffix}"] = std
        if rank:
            results[f"rank_{name_suffix}"] = np.asarray(
                rankdata(-mean, method="min"), dtype=np.int32
            )

    for m in metric_names:
        suffix = f"test_{m}" if multimetric else "test_score"
        table = np.array(
            [s[0][m] for s in scores], dtype=np.float64
        ).reshape(n_candidates, n_splits)
        w = None
        if test_weights is not None:
            w = np.asarray(test_weights, dtype=np.float64).reshape(
                n_candidates, n_splits
            )
        _store(suffix, table, weights=w, rank=True)
        if return_train_score:
            tsuffix = f"train_{m}" if multimetric else "train_score"
            ttable = np.array(
                [
                    (s[1][m] if s[1] is not None else np.nan)
                    for s in scores
                ],
                dtype=np.float64,
            ).reshape(n_candidates, n_splits)
            _store(tsuffix, ttable)

    return results
