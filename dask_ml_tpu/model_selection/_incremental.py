"""Asynchronous successive-halving / Hyperband search on the elastic
data plane (reference: dask_ml/model_selection/_incremental.py,
_successive_halving.py, _hyperband.py).

The grid/random driver (``_search.py``) is SYNCHRONOUS: one generation,
every candidate fit to completion, a single straggler cell gating the
sweep, budget spent equally on doomed candidates. This module spends the
budget at progressively finer resolution on survivors only — dask-ml's
own later-era flagship (PAPER.md pillar 4), rebuilt on the substrate
this repo owns instead of dask futures:

- **rungs are epochs over the elastic data plane**: training data is
  split once into host-side blocks; a rung advances every surviving
  candidate ``partial_fit``-wise through N epochs whose per-epoch block
  order is a seeded :class:`~dask_ml_tpu.parallel.elastic.BlockPlan`
  permutation — a pure function of (seed, epoch), so every host and
  every resume replays the identical stream.
- **promotion is host-side arithmetic over journaled scores**: each
  (candidate, rung) result — validation score AND the candidate's full
  post-rung model state — is one content-addressed
  :class:`~dask_ml_tpu.checkpoint.CellJournal` record. Keep the top
  ``1/aggressiveness`` by (score, lowest id) and multiply the epoch
  budget; a killed search resumes mid-bracket and reproduces the
  remaining rungs bit-identically, because a rung result is a pure
  function of (rung-start journaled state, seeded epoch orders).
- **asynchronous promotion ≠ compile storm**: candidates of a bracket
  advance through ONE jitted program
  (:func:`dask_ml_tpu.models.glm.make_batched_sgd_epoch`) whose
  per-member hyperparameters are traced vectors and whose fixed batch
  width carries an alive-mask — a promotion shrinks the mask, never a
  shape, so after a bracket's first rung (where every candidate and
  every program runs) later rungs execute ZERO fresh heavy compiles
  (gated per rung via
  :func:`~dask_ml_tpu.parallel.shapes.track_compiles`). Estimators
  outside the batched fast path (e.g. ``MiniBatchKMeans``) run
  per-candidate ``partial_fit`` whose jitted step/score programs all
  compile in rung 0 for the same reason.
- **multi-host**: pass ``elastic=`` an
  :class:`~dask_ml_tpu.parallel.elastic.ElasticRun` and the rung's
  (candidate × rung) work items become elastic BLOCKS — each host
  computes its contiguous share, publishes atomically, and
  ``collect_epoch`` handles death re-deals plus the speculative
  straggler re-deal (``speculate_after``). Candidate results are pure,
  so any host recomputing one reproduces its bytes: a kill-one-host
  drill mid-search drops zero candidates and changes zero bits.

Timeout semantics differ from the synchronous driver's by design: a
cell that exceeds ``cell_timeout`` there scores ``error_score``; a
STREAMING candidate that exceeds the per-rung deadline keeps its last
COMPLETED rung's journaled score and is merely stopped (degraded, not
deleted) — a straggler loses the promotion race, not its history.
"""

from __future__ import annotations

import logging
import pickle
import time
from typing import Optional

import numpy as np
from sklearn.base import BaseEstimator, MetaEstimatorMixin, clone
from sklearn.model_selection import ParameterGrid, ParameterSampler

from dask_ml_tpu.model_selection._search import (
    _content_array,
    _index,
    _n_rows,
    _scoring_identity,
    run_with_soft_deadline,
)
from dask_ml_tpu.model_selection._tokenize import tokenize
from dask_ml_tpu.parallel import telemetry

logger = logging.getLogger(__name__)

__all__ = ["SuccessiveHalvingSearchCV", "HyperbandSearchCV",
           "bracket_rungs", "hyperband_brackets"]


# ---------------------------------------------------------------------------
# bracket arithmetic (pure, host-side — what the tests hand-compute)
# ---------------------------------------------------------------------------


def bracket_rungs(n0: int, r0: int, eta: int,
                  max_epochs: Optional[int] = None) -> list:
    """The successive-halving schedule for one bracket:
    ``[(rung, n_alive, cumulative_epochs)]``.

    Rung k holds ``n_k`` candidates trained to ``r_k`` TOTAL epochs;
    promotion keeps ``max(1, n_k // eta)`` of them and multiplies the
    budget by ``eta`` (capped at ``max_epochs``). With ``max_epochs``
    set, a lone survivor still trains on to the cap (the classic
    Hyperband last rung); without it the bracket ends at the first rung
    a single candidate survives.
    """
    eta = int(eta)
    if eta < 2:
        raise ValueError(f"aggressiveness must be >= 2, got {eta}")
    cap = None if max_epochs is None else int(max_epochs)
    n, r, k = int(n0), int(r0), 0
    if cap is not None:
        r = min(r, cap)
    out = []
    while True:
        out.append((k, n, r))
        if (n == 1 and (cap is None or r >= cap)) or (
                cap is not None and r >= cap):
            return out
        n = max(1, n // eta)
        r = r * eta if cap is None else min(r * eta, cap)
        k += 1


def hyperband_brackets(max_epochs: int, eta: int) -> list:
    """The Hyperband bracket set ``[(s, n0, r0)]``, most exploratory
    first: ``s_max = floor(log_eta(max_epochs))`` brackets trading
    initial candidates against initial epochs at roughly equal total
    budget (Li et al., arxiv 1603.06560 — the bracket arithmetic
    dask-ml's ``HyperbandSearchCV`` uses)."""
    eta = int(eta)
    R = int(max_epochs)
    if eta < 2:
        raise ValueError(f"aggressiveness must be >= 2, got {eta}")
    if R < 1:
        raise ValueError(f"max_epochs must be >= 1, got {R}")
    s_max = int(np.floor(np.log(R) / np.log(eta)))
    out = []
    for s in range(s_max, -1, -1):
        n0 = int(np.ceil((s_max + 1) / (s + 1) * eta ** s))
        r0 = max(1, int(R * eta ** -s))
        out.append((s, n0, r0))
    return out


class _RungTimeout(Exception):
    """Internal: a candidate's rung exceeded the soft deadline."""

    def __init__(self, cid: int):
        super().__init__(f"candidate {cid} rung timed out")
        self.cid = cid


def _record_to_tree(rec: Optional[dict]) -> dict:
    """A rung record as a numpy pytree for atomic elastic publication
    (``save_pytree`` frames arrays, not arbitrary objects). ``None``
    (a timed-out candidate) publishes a sentinel so peers' rung
    assembly never blocks on a straggler that was already degraded."""
    if rec is None:
        return {"timeout": np.int64(1)}
    return {
        "score": np.float64(rec["score"]),
        "blob": np.frombuffer(rec["blob"], dtype=np.uint8).copy(),
        "n_epochs": np.int64(rec["n_epochs"]),
        "pf_calls": np.int64(rec["pf_calls"]),
        "fit_seconds": np.float64(rec["fit_seconds"]),
        "score_seconds": np.float64(rec["score_seconds"]),
    }


def _tree_to_record(tree: dict) -> Optional[dict]:
    if "timeout" in tree:
        return None
    return {
        "score": float(tree["score"]),
        "blob": np.asarray(tree["blob"], dtype=np.uint8).tobytes(),
        "n_epochs": int(tree["n_epochs"]),
        "pf_calls": int(tree["pf_calls"]),
        "fit_seconds": float(tree["fit_seconds"]),
        "score_seconds": float(tree["score_seconds"]),
    }


# ---------------------------------------------------------------------------
# the driver
# ---------------------------------------------------------------------------


class BaseIncrementalSearchCV(BaseEstimator, MetaEstimatorMixin):
    """Shared machinery of the incremental (``partial_fit``) searches;
    subclasses define the bracket set (:meth:`_brackets`) and their
    constructor surface. See the module docstring for the architecture.
    """

    # -- subclass surface -------------------------------------------------

    def _brackets(self) -> list:
        raise NotImplementedError  # pragma: no cover - abstract

    def _draw_candidates(self, bracket: int, n0: int) -> list:
        """The bracket's parameter draw: the full grid when
        ``n_initial_parameters='grid'``, otherwise a seeded
        ``ParameterSampler`` draw (per-bracket seed, so Hyperband
        brackets explore different points)."""
        if getattr(self, "n_initial_parameters", None) == "grid":
            grid = list(ParameterGrid(self.parameters))
            return grid
        return list(ParameterSampler(
            self.parameters, n0,
            random_state=int(self.random_state) + 1000 * int(bracket)))

    # -- scoring ----------------------------------------------------------

    def _score_estimator(self, est, X_val, y_val) -> float:
        if callable(self.scoring):
            return float(self.scoring(est, X_val, y_val))
        if self.scoring not in (None, "passthrough"):
            raise ValueError(
                "incremental search supports scoring=None (the "
                "estimator's own score) or a callable(est, X, y); got "
                f"{self.scoring!r}")
        if y_val is None:
            return float(est.score(X_val))
        return float(est.score(X_val, y_val))

    # -- batched fast path (one program per bracket) ----------------------

    def _plan_batched(self, est, params_list, y_train, classes):
        """Eligibility + member arrays for the batched rung program.
        Returns ``None`` (fall back to per-candidate ``partial_fit``)
        unless every candidate of the bracket is the SAME streaming GLM
        problem at different (lamduh, eta0, power_t) — the only knobs
        :func:`~dask_ml_tpu.models.glm.make_batched_sgd_epoch` traces.
        """
        if not getattr(self, "batched_rungs", True):
            return None
        if self.scoring not in (None, "passthrough"):
            return None
        if not hasattr(est, "_sgd_config"):
            return None
        if getattr(est, "family", None) not in ("logistic", "normal"):
            return None
        if y_train is None:
            return None
        cfgs = []
        for p in params_list:
            if not set(p) <= {"C", "solver_kwargs"}:
                return None
            sk = p.get("solver_kwargs")
            if sk is not None and not set(sk) <= {"eta0", "power_t"}:
                return None
            try:
                cfgs.append(clone(est).set_params(**p)._sgd_config())
            except Exception:
                return None
        base = [(c["family"], c["regularizer"], c["fit_intercept"],
                 c.get("n_classes")) for c in cfgs]
        if len(set(base)) != 1 or base[0][3] is not None:
            return None
        # encoding reference: pins the class set (binary only — the
        # softmax stream state is (width, K), outside the batched
        # program) and owns _encode_eval_y for validation scoring
        ref = clone(est)
        try:
            y_enc = ref._encode_y_partial(np.asarray(y_train), classes)
        except Exception:
            return None
        if len(getattr(ref, "_pf_classes", [0, 1])) > 2:
            return None
        lam = np.asarray([c["lamduh"] for c in cfgs], np.float32)
        eta0 = np.asarray([c["eta0"] for c in cfgs], np.float32)
        power_t = np.asarray([c["power_t"] for c in cfgs], np.float32)
        fam, reg, fi, _ = base[0]
        return {"ref": ref, "y_enc": y_enc, "lam": lam, "eta0": eta0,
                "power_t": power_t, "family": fam, "regularizer": reg,
                "fit_intercept": bool(fi)}

    # -- fit --------------------------------------------------------------

    def fit(self, X, y=None, classes=None, **fit_params):
        if fit_params:
            raise ValueError(
                "incremental search streams raw blocks through "
                f"partial_fit; fit_params {sorted(fit_params)} are not "
                "supported")
        from dask_ml_tpu.parallel.elastic import BlockPlan
        from dask_ml_tpu.parallel.shapes import track_compiles

        t_fit0 = time.time()
        est = self.estimator
        eta = int(self.aggressiveness)
        if eta < 2:
            raise ValueError(
                f"aggressiveness must be >= 2, got {self.aggressiveness}")
        run = self.elastic

        # -- deterministic holdout split + block partition ----------------
        n = _n_rows(X)
        rng = np.random.RandomState(self.random_state)
        perm = rng.permutation(n)
        n_test = max(1, int(round(float(self.test_size) * n)))
        if n_test >= n:
            raise ValueError(
                f"test_size={self.test_size} leaves no training rows "
                f"(n={n})")
        test_idx = np.sort(perm[:n_test])
        train_pool = perm[n_test:]
        n_blocks = max(1, min(int(self.n_blocks), len(train_pool)))
        n_used = (len(train_pool) // n_blocks) * n_blocks
        train_idx = train_pool[:n_used]  # tail trim: equal block shapes
        block_rows = np.split(train_idx, n_blocks)
        data_plan = BlockPlan(n_blocks, seed=int(self.shuffle_seed),
                              shuffle=True)
        Xblocks = [_index(X, bi) for bi in block_rows]
        yblocks = (None if y is None
                   else [_index(y, bi) for bi in block_rows])
        y_train = None if y is None else _index(y, train_idx)
        X_val = _index(X, test_idx)
        y_val = None if y is None else _index(y, test_idx)

        # -- brackets + candidates ----------------------------------------
        brackets = self._brackets()
        cand_params: list = []      # cid -> params dict
        cand_bracket: list = []     # cid -> bracket id s
        cand_model_id: list = []
        bracket_cids: dict = {}     # s -> [cid]
        for s, n0, _r0 in brackets:
            cids = []
            for i, p in enumerate(self._draw_candidates(s, n0)):
                cid = len(cand_params)
                cand_params.append(p)
                cand_bracket.append(s)
                cand_model_id.append(f"bracket={s}-{i}")
                cids.append(cid)
            bracket_cids[s] = cids

        # -- journal (content-addressed resume) ---------------------------
        journal = None
        done: dict = {}
        est_token = None
        scoring_id = _scoring_identity(self.scoring)
        if self.checkpoint:
            from dask_ml_tpu.checkpoint import CellJournal

            # per-rank journal path under an elastic roster: concurrent
            # processes must not interleave appends in one file; each
            # host's journal alone is enough to resume it (and the
            # namespace's published blocks cover the rest of the fleet)
            path = (f"{self.checkpoint}.r{run.rank}" if run is not None
                    else self.checkpoint)
            journal = CellJournal(path)
            done = journal.load()
        est_token = tokenize(
            type(est), est.get_params(deep=True), _content_array(X),
            _content_array(y), classes if classes is None
            else _content_array(classes))

        def rung_key(cid, rung, cum):
            return tokenize(
                "rung", est_token, cand_params[cid], cand_bracket[cid],
                rung, cum, n_blocks, int(self.shuffle_seed), scoring_id,
                _content_array(test_idx))

        if run is not None:
            run.bind_problem(
                "asha", token=est_token,
                grid=tokenize(cand_params), eta=eta, n_blocks=n_blocks,
                seed=int(self.shuffle_seed),
                scoring=scoring_id)
        elastic_before = (
            (run.blocks_rebalanced, run.blocks_speculated)
            if run is not None else (0, 0))

        # -- fit-wide state -----------------------------------------------
        records: dict = {}      # cid -> latest completed-rung record
        cand_rung: dict = {}    # cid -> last completed rung index
        cand_status: dict = {}
        history: list = []
        rung_table: list = []
        self.n_rungs_completed_ = 0
        self.n_promotions_ = 0
        self.n_candidates_stopped_ = 0
        self.n_rung_timeouts_ = 0
        self.n_rung_retries_ = 0
        self.n_resumed_rungs_ = 0
        self.n_plateau_stops_ = 0
        self.rung_compile_stats_ = []
        budget_spent = [0]

        # plateau stop (patience): a candidate whose journaled rung
        # scores improve < tol for `patience` consecutive scored rungs
        # stops early even if its RANK would have promoted it — rank
        # can stay high while learning has stalled, and a stalled
        # candidate's remaining epochs are pure budget leak
        patience_n = getattr(self, "patience", None)
        patience_n = None if patience_n is None else int(patience_n)
        if patience_n is not None and patience_n < 1:
            raise ValueError(f"patience must be >= 1, got {patience_n}")
        plateau_tol = float(getattr(self, "tol", 1e-3) or 0.0)
        plateau_best: dict = {}    # cid -> best score seen (ratchet)
        plateau_streak: dict = {}  # cid -> consecutive sub-tol rungs

        cap = getattr(self, "max_epochs", None)
        cap = None if cap is None else int(cap)
        deepest = [0]

        # one batched plan per bracket (fixed batch width = the
        # bracket's n0: a promotion changes the alive-MASK, not a shape)
        bplans = {}
        bstage: dict = {}  # lazy device stacks shared by every bracket

        def batched_stage(bplan):
            if "Xb" in bstage:
                return bstage
            import jax.numpy as jnp

            Xb = np.stack([np.asarray(b, np.float32) for b in Xblocks])
            if bplan["fit_intercept"]:
                Xb = np.concatenate(
                    [Xb, np.ones(Xb.shape[:2] + (1,), np.float32)],
                    axis=2)
            yb = np.asarray(bplan["y_enc"], np.float32).reshape(
                n_blocks, -1)
            wb = np.ones(yb.shape, np.float32)
            Ev = np.asarray(X_val, np.float32)
            if bplan["fit_intercept"]:
                Ev = np.concatenate(
                    [Ev, np.ones((Ev.shape[0], 1), np.float32)], axis=1)
            yv = np.asarray(
                bplan["ref"]._encode_eval_y(np.asarray(y_val)),
                np.float32)
            wv = np.ones(yv.shape, np.float32)
            bstage.update(
                Xb=jnp.asarray(Xb), yb=jnp.asarray(yb),
                wb=jnp.asarray(wb), Ev=jnp.asarray(Ev),
                yv=jnp.asarray(yv), wv=jnp.asarray(wv),
                width=int(Xb.shape[2]))
            return bstage

        def train_generic_one(cid, prev_cum, cum):
            """One candidate's rung: restore (or build) the estimator,
            stream (cum - prev_cum) seeded epochs of partial_fit blocks,
            score on the holdout. Pure in (previous record, epoch
            seeds), which is what makes re-deals and resumes
            bit-identical."""
            prev = records.get(cid)
            t0 = time.time()
            if prev is None:
                m = clone(est).set_params(**cand_params[cid])
            else:
                m = pickle.loads(prev["blob"])
            calls = 0
            for e in range(prev_cum, cum):
                for b in data_plan.epoch_order(e):
                    if yblocks is None:
                        m.partial_fit(Xblocks[b])
                    elif classes is not None:
                        m.partial_fit(Xblocks[b], yblocks[b],
                                      classes=classes)
                    else:
                        m.partial_fit(Xblocks[b], yblocks[b])
                    calls += 1
            t1 = time.time()
            score = self._score_estimator(m, X_val, y_val)
            return {
                "score": score, "blob": pickle.dumps(m),
                "n_epochs": cum,
                "pf_calls": (0 if prev is None else prev["pf_calls"])
                + calls,
                "fit_seconds": t1 - t0, "score_seconds": time.time() - t1,
            }

        def train_batched_all(s, bplan, need, prev_cum, cum):
            """The whole bracket's rung as ONE program: stacked (M,
            width) states advance through the seeded epochs with traced
            per-member hyperparameters and an alive-mask (stopped lanes
            freeze; their values cannot reach live lanes — vmap member
            independence, which is also why any elastic host recomputes
            any member bit-identically). Scores all lanes in one
            batched pass; materializes per-candidate estimators only
            for ``need``."""
            import jax.numpy as jnp

            from dask_ml_tpu.models import glm as glm_core

            stage = batched_stage(bplan)
            cids = bracket_cids[s]
            M, width = len(cids), stage["width"]
            betas = np.zeros((M, width), np.float32)
            ts = np.zeros((M,), np.float32)
            live = np.zeros((M,), bool)
            for j, cid in enumerate(cids):
                if cid in need:
                    live[j] = True
                prev = records.get(cid)
                if prev is not None:
                    beta, t = pickle.loads(prev["blob"])._pf_state
                    betas[j], ts[j] = beta, t
            t0 = time.time()
            ep_fn = glm_core.get_batched_sgd_epoch(
                bplan["family"], bplan["regularizer"],
                bplan["fit_intercept"])
            db, dt = jnp.asarray(betas), jnp.asarray(ts)
            lam, e0, pt = (jnp.asarray(bplan["lam"]),
                           jnp.asarray(bplan["eta0"]),
                           jnp.asarray(bplan["power_t"]))
            lv = jnp.asarray(live)
            for e in range(prev_cum, cum):
                order = jnp.asarray(data_plan.epoch_order(e), jnp.int32)
                db, dt = ep_fn(db, dt, lam, e0, pt, lv,
                               stage["Xb"], stage["yb"], stage["wb"],
                               order)
            t1 = time.time()
            scores = np.asarray(glm_core.batched_eval_scores(
                stage["Ev"], stage["yv"], stage["wv"], db,
                family=bplan["family"]))
            nb, nt = np.asarray(db), np.asarray(dt)
            t2 = time.time()
            n_need = max(len(need), 1)
            out = {}
            ref = bplan["ref"]
            for j, cid in enumerate(cids):
                if cid not in need:
                    continue
                m = clone(est).set_params(**cand_params[cid])
                pf = getattr(ref, "_pf_classes", None)
                if pf is not None:
                    m._pf_classes = np.asarray(pf)
                    m.classes_ = np.asarray(pf)
                m._store_pf_state((nb[j], float(nt[j])))
                prev = records.get(cid)
                out[cid] = {
                    "score": float(scores[j]), "blob": pickle.dumps(m),
                    "n_epochs": cum,
                    "pf_calls": (0 if prev is None
                                 else prev["pf_calls"])
                    + (cum - prev_cum) * n_blocks,
                    "fit_seconds": (t1 - t0) / n_need,
                    "score_seconds": (t2 - t1) / n_need,
                }
            return out

        def run_rung(s, rung, uid, alive, prev_cum, cum):
            """Compute/restore every alive candidate's rung record.
            Returns {cid: record}; a timed-out candidate maps to None.
            """
            keys = {cid: rung_key(cid, rung, cum) for cid in alive}
            restored = {cid: done[k] for cid, k in keys.items()
                        if k in done}
            self.n_resumed_rungs_ += len(restored)
            need = [cid for cid in alive if cid not in restored]
            bplan = bplans.get(s)
            bmemo: dict = {}

            def make_record(cid):
                # the elastic compute_publish unit — also the local path
                if cid in restored:
                    return restored[cid]
                if bplan is not None:
                    if not bmemo:
                        bmemo.update(train_batched_all(
                            s, bplan, set(need), prev_cum, cum))
                    return bmemo[cid]
                last_err = None
                for _attempt in range(int(self.cell_retries) + 1):
                    try:
                        value, timed_out = run_with_soft_deadline(
                            lambda: train_generic_one(
                                cid, prev_cum, cum),
                            self.cell_timeout,
                            name=f"asha-rung-{s}-{rung}-{cid}")
                        if timed_out:
                            raise _RungTimeout(cid)
                        return value
                    except _RungTimeout:
                        raise
                    except Exception as e:
                        last_err = e
                        self.n_rung_retries_ += 1
                        telemetry.counter("search.rung_retries").inc()
                        logger.warning(
                            "asha: candidate %d rung %d attempt failed "
                            "(%s); retrying", cid, rung, e)
                raise last_err

            results = {}
            if run is None:
                for cid in alive:
                    try:
                        results[cid] = make_record(cid)
                    except _RungTimeout:
                        results[cid] = None
            else:
                results = self._rung_elastic(
                    run, uid, list(alive), make_record)
            if journal is not None:
                for cid in alive:
                    rec = results.get(cid)
                    k = keys[cid]
                    # timeouts are never journaled: a resume retries them
                    if rec is not None and k not in done:
                        journal.append(k, rec)
                        done[k] = rec
            return results

        # -- bracket loop -------------------------------------------------
        from dask_ml_tpu.parallel.shapes import compile_stats  # noqa: F401

        for s, n0, r0 in brackets:
            cids0 = bracket_cids[s]
            bplan = self._plan_batched(
                est, [cand_params[c] for c in cids0], y_train, classes)
            if bplan is not None:
                bplans[s] = bplan
            alive = list(cids0)
            for cid in alive:
                cand_status[cid] = "running"
            rung, prev_cum = 0, 0
            cum = r0 if cap is None else min(r0, cap)
            with telemetry.span("search.bracket", bracket=s,
                                candidates=n0, r0=r0):
                while True:
                    uid = 1000 * (s + 1) + rung
                    with telemetry.span("search.rung", bracket=s,
                                        rung=rung,
                                        candidates=len(alive)), \
                            track_compiles() as tc:
                        results = run_rung(s, rung, uid, alive,
                                           prev_cum, cum)
                    self.rung_compile_stats_.append({
                        "bracket": s, "rung": rung,
                        "candidates": len(alive),
                        "n_compiles": int(tc["n_compiles"]),
                    })
                    self.n_rungs_completed_ += 1
                    telemetry.counter("search.rungs_completed").inc()
                    budget_spent[0] += (cum - prev_cum) * len(alive)
                    deepest[0] = max(deepest[0], cum)
                    timeouts = [cid for cid in alive
                                if results.get(cid) is None]
                    for cid in timeouts:
                        # the satellite fix: degrade, don't delete — the
                        # candidate keeps its LAST completed rung score
                        self.n_rung_timeouts_ += 1
                        telemetry.counter("search.rung_timeouts").inc()
                        cand_status[cid] = "stopped (rung timeout)"
                        logger.warning(
                            "asha: candidate %d timed out at bracket %d "
                            "rung %d; keeping its rung-%d score", cid, s,
                            rung, rung - 1)
                    survivors = [cid for cid in alive
                                 if results.get(cid) is not None]
                    for cid in survivors:
                        records[cid] = results[cid]
                        cand_rung[cid] = rung
                        history.append({
                            "model_id": cand_model_id[cid],
                            "bracket": s, "rung": rung,
                            "n_epochs": cum,
                            "score": results[cid]["score"],
                            "partial_fit_calls":
                                results[cid]["pf_calls"],
                            "elapsed_wall_time": time.time() - t_fit0,
                        })
                    survivors.sort(
                        key=lambda cid: (-records[cid]["score"], cid))
                    final = (len(survivors) <= 1
                             and (cap is None or cum >= cap)) or (
                                 cap is not None and cum >= cap)
                    plateaued: list = []
                    if patience_n is not None and not final:
                        keep = []
                        for cid in survivors:
                            sc = records[cid]["score"]
                            best = plateau_best.get(cid)
                            if best is None or sc > best + plateau_tol:
                                plateau_best[cid] = (
                                    sc if best is None else max(sc, best))
                                plateau_streak[cid] = 0
                                keep.append(cid)
                                continue
                            plateau_streak[cid] = (
                                plateau_streak.get(cid, 0) + 1)
                            if plateau_streak[cid] >= patience_n:
                                plateaued.append(cid)
                                cand_status[cid] = "stopped (plateau)"
                            else:
                                keep.append(cid)
                        survivors = keep
                        if plateaued:
                            self.n_plateau_stops_ += len(plateaued)
                            telemetry.counter(
                                "search.plateau_stops").inc(len(plateaued))
                    if final:
                        n_next = len(survivors)
                        promoted, stopped = survivors, []
                    else:
                        n_next = max(1, len(survivors) // eta)
                        promoted = survivors[:n_next]
                        stopped = survivors[n_next:]
                    for cid in stopped:
                        cand_status[cid] = "stopped"
                    self.n_promotions_ += 0 if final else len(promoted)
                    if not final and promoted:
                        telemetry.counter("search.promotions").inc(
                            len(promoted))
                    if stopped or timeouts or plateaued:
                        self.n_candidates_stopped_ += (
                            len(stopped) + len(timeouts) + len(plateaued))
                        telemetry.counter(
                            "search.candidates_stopped").inc(
                            len(stopped) + len(timeouts) + len(plateaued))
                    rung_table.append({
                        "bracket": s, "rung": rung, "n_epochs": cum,
                        "alive": len(alive),
                        "scored": len(survivors) + len(plateaued),
                        "promoted": 0 if final else len(promoted),
                        "stopped": len(stopped), "timeouts":
                            len(timeouts), "plateau": len(plateaued),
                        "final": bool(final),
                    })
                    if final:
                        for cid in promoted:
                            cand_status[cid] = "stopped"
                        if promoted:
                            cand_status[promoted[0]] = "best in bracket"
                        break
                    if not promoted:
                        break  # every candidate timed out
                    alive = promoted
                    rung += 1
                    prev_cum = cum
                    cum = cum * eta if cap is None else min(cum * eta,
                                                            cap)

        if not records:
            raise RuntimeError(
                "incremental search finished with no scored candidate "
                "(every rung-0 candidate timed out)")

        # -- results ------------------------------------------------------
        self._build_results(
            cand_params, cand_bracket, cand_model_id, cand_rung,
            cand_status, records, history, rung_table, brackets,
            budget_spent[0], deepest[0], n_blocks)
        if run is not None:
            self.n_blocks_rebalanced_ = (run.blocks_rebalanced
                                         - elastic_before[0])
            self.n_blocks_speculated_ = (run.blocks_speculated
                                         - elastic_before[1])
        else:
            self.n_blocks_rebalanced_ = 0
            self.n_blocks_speculated_ = 0
        return self

    # -- elastic rung -----------------------------------------------------

    def _rung_elastic(self, run, uid, cids, make_record) -> dict:
        """One rung over the elastic plane: the rung's candidates are
        the epoch's BLOCKS (identity order — candidate shards need no
        shuffling; the DATA epochs inside each candidate are the seeded
        permutations), dealt contiguously over the live roster. Each
        host computes its share, publishes atomically, and
        ``collect_epoch`` re-deals the blocks of dead hosts (and — with
        ``speculate_after`` — of merely slow ones) to survivors. A
        candidate's rung is a pure function of its journaled state and
        the seeds, so whichever host computes it publishes identical
        bytes: first publication wins."""
        from dask_ml_tpu.parallel.elastic import (BlockPlan,
                                                  _epoch_assignment)

        order = list(range(len(cids)))
        plan = BlockPlan(len(order), seed=0, shuffle=False)
        owner = _epoch_assignment(run, order)

        def compute_publish(grab):
            for b in grab:
                try:
                    rec = make_record(cids[b])
                except _RungTimeout:
                    rec = None
                run.publish(uid, b, _record_to_tree(rec))
                run.beat()
                run.maybe_die(b, uid)

        have = run.published(uid)
        mine = [b for b in order
                if owner.get(b) == run.rank and b not in have]
        compute_publish(mine)
        out = run.collect_epoch(plan, uid, order, owner, compute_publish)
        return {cids[b]: _tree_to_record(out[b]) for b in order}

    # -- cv_results_ ------------------------------------------------------

    def _build_results(self, cand_params, cand_bracket, cand_model_id,
                       cand_rung, cand_status, records, history,
                       rung_table, brackets, budget_spent, deepest,
                       n_blocks):
        n_models = len(cand_params)
        scores = np.full(n_models, np.nan)
        n_epochs = np.zeros(n_models, np.int64)
        pf_calls = np.zeros(n_models, np.int64)
        rung_arr = np.full(n_models, -1, np.int64)
        fit_t = np.zeros(n_models)
        score_t = np.zeros(n_models)
        for cid, rec in records.items():
            scores[cid] = rec["score"]
            n_epochs[cid] = rec["n_epochs"]
            pf_calls[cid] = rec["pf_calls"]
            rung_arr[cid] = cand_rung[cid]
            fit_t[cid] = rec["fit_seconds"] / max(rec["n_epochs"], 1)
            score_t[cid] = rec["score_seconds"]
        order = sorted(
            range(n_models),
            key=lambda c: (-(scores[c] if np.isfinite(scores[c])
                             else -np.inf), c))
        rank = np.zeros(n_models, np.int32)
        for pos, cid in enumerate(order):
            if pos > 0 and scores[cid] == scores[order[pos - 1]]:
                rank[cid] = rank[order[pos - 1]]
            else:
                rank[cid] = pos + 1
        keys = sorted({k for p in cand_params for k in p})
        results = {
            "params": np.asarray(cand_params, dtype=object),
            "model_id": np.asarray(cand_model_id, dtype=object),
            "bracket_": np.asarray(cand_bracket, np.int64),
            "rung_": rung_arr,
            "n_epochs_": n_epochs,
            "partial_fit_calls": pf_calls,
            "test_score": scores,
            "rank_test_score": rank,
            "mean_partial_fit_time": fit_t,
            "mean_score_time": score_t,
            "status": np.asarray(
                [cand_status.get(c, "running") for c in range(n_models)],
                dtype=object),
        }
        for k in keys:
            results[f"param_{k}"] = np.asarray(
                [p.get(k, np.nan) for p in cand_params], dtype=object)
        self.cv_results_ = results
        self.history_ = history
        self.rung_table_ = rung_table
        best = order[0]
        self.best_index_ = int(best)
        self.best_score_ = float(scores[best])
        self.best_params_ = cand_params[best]
        self.best_estimator_ = pickle.loads(records[best]["blob"])
        self.multimetric_ = False
        self.scorer_ = self.scoring
        self.n_splits_ = 1
        sync = n_models * deepest
        self.budget_spent_ = int(budget_spent)
        self.budget_synchronous_ = int(sync)
        self.metadata_ = {
            "n_models": n_models,
            "partial_fit_calls": int(pf_calls.sum()),
            "fit_epochs": int(budget_spent),
            "fit_epochs_synchronous": int(sync),
            "brackets": [
                {"bracket": s, "n_models": n0, "r0": r0,
                 "rungs": bracket_rungs(
                     n0, r0, int(self.aggressiveness),
                     getattr(self, "max_epochs", None))}
                for s, n0, r0 in brackets
            ],
        }

    # -- introspection ----------------------------------------------------

    def shared_fit_report(self) -> str:
        """The incremental analogue of the synchronous driver's
        work-sharing report: the rung table (candidates alive /
        promoted / stopped per rung), straggler re-deals, and the
        fit-epoch budget against the synchronous grid equivalent —
        the evidence that budget concentrated on survivors."""
        if not hasattr(self, "rung_table_"):
            raise AttributeError("Not fitted; call fit first")
        md = self.metadata_
        pct = 100.0 * md["fit_epochs"] / max(
            md["fit_epochs_synchronous"], 1)
        lines = [
            (f"{md['n_models']} candidates over "
             f"{self.n_rungs_completed_} rungs: "
             f"{md['fit_epochs']} fit-epochs spent vs "
             f"{md['fit_epochs_synchronous']} synchronous-equivalent "
             f"({pct:.0f}%)"),
            "",
            (f"{'bracket':>7} {'rung':>4} {'epochs':>6} {'alive':>5} "
             f"{'promoted':>8} {'stopped':>7} {'timeouts':>8} "
             f"{'plateau':>7}"),
        ]
        for row in self.rung_table_:
            lines.append(
                f"{row['bracket']:>7} {row['rung']:>4} "
                f"{row['n_epochs']:>6} {row['alive']:>5} "
                f"{row['promoted']:>8} {row['stopped']:>7} "
                f"{row['timeouts']:>8} {row.get('plateau', 0):>7}")
        extras = []
        if self.n_blocks_rebalanced_ or self.n_blocks_speculated_:
            extras.append(
                f"{self.n_blocks_rebalanced_} candidate-rung(s) "
                f"re-dealt from lost hosts, "
                f"{self.n_blocks_speculated_} speculatively re-dealt "
                f"from stragglers")
        if self.n_resumed_rungs_:
            extras.append(
                f"{self.n_resumed_rungs_} candidate-rung(s) restored "
                "from the journal")
        if self.n_rung_retries_ or self.n_rung_timeouts_:
            extras.append(
                f"{self.n_rung_retries_} rung retr"
                f"{'y' if self.n_rung_retries_ == 1 else 'ies'}, "
                f"{self.n_rung_timeouts_} rung timeout"
                f"{'' if self.n_rung_timeouts_ == 1 else 's'} "
                "(degraded to last completed rung)")
        if getattr(self, "n_plateau_stops_", 0):
            extras.append(
                f"{self.n_plateau_stops_} candidate"
                f"{'' if self.n_plateau_stops_ == 1 else 's'} "
                f"plateau-stopped (< {getattr(self, 'tol', 1e-3)} score "
                f"improvement for {getattr(self, 'patience', '?')} "
                "rungs)")
        if extras:
            lines += [""] + extras
        if telemetry.enabled() or telemetry.spans():
            lines += ["", telemetry.render_report()]
        return "\n".join(lines)

    # -- post-fit delegation ----------------------------------------------

    def _check_is_fitted(self, method_name):
        if not hasattr(self, "best_estimator_"):
            raise AttributeError("Not fitted; call fit first")

    @property
    def classes_(self):
        self._check_is_fitted("classes_")
        return self.best_estimator_.classes_

    def predict(self, X):
        self._check_is_fitted("predict")
        return self.best_estimator_.predict(X)

    def predict_proba(self, X):
        self._check_is_fitted("predict_proba")
        return self.best_estimator_.predict_proba(X)

    def decision_function(self, X):
        self._check_is_fitted("decision_function")
        return self.best_estimator_.decision_function(X)

    def transform(self, X):
        self._check_is_fitted("transform")
        return self.best_estimator_.transform(X)

    def score(self, X, y=None):
        self._check_is_fitted("score")
        return self._score_estimator(self.best_estimator_, X, y)


class SuccessiveHalvingSearchCV(BaseIncrementalSearchCV):
    """Asynchronous successive halving (ASHA) over ``partial_fit``
    estimators — ONE bracket of :func:`bracket_rungs`.

    ``n_initial_parameters`` is the rung-0 candidate count drawn from
    ``parameters`` with a seeded ``ParameterSampler``, or the string
    ``'grid'`` for the full ``ParameterGrid`` (the bench's
    finds-the-grid-optimum configuration). ``n_initial_epochs`` is the
    rung-0 budget; each promotion keeps the top ``1/aggressiveness`` of
    the scored candidates and multiplies the cumulative epoch budget by
    ``aggressiveness``, up to ``max_epochs``.

    ``patience`` (optional) adds a plateau stop on top of the halving
    rule: a candidate whose journaled rung score improves by less than
    ``tol`` for ``patience`` consecutive rungs is stopped even if it
    would otherwise be promoted. Plateau stops are counted in
    ``n_plateau_stops_`` and reported per rung in ``rung_table_``
    (``plateau`` column). See the module docstring
    for rung/epoch semantics, journaling, batching, and the elastic
    plane; see :class:`HyperbandSearchCV` for the multi-bracket sweep.
    """

    def __init__(self, estimator, parameters, *,
                 n_initial_parameters=10, n_initial_epochs=1,
                 aggressiveness=3, max_epochs=None, test_size=0.2,
                 n_blocks=4, shuffle_seed=0, random_state=0,
                 scoring=None, checkpoint=None, cell_timeout=None,
                 cell_retries=0, elastic=None, batched_rungs=True,
                 patience=None, tol=1e-3):
        self.estimator = estimator
        self.parameters = parameters
        self.n_initial_parameters = n_initial_parameters
        self.n_initial_epochs = n_initial_epochs
        self.aggressiveness = aggressiveness
        self.max_epochs = max_epochs
        self.test_size = test_size
        self.n_blocks = n_blocks
        self.shuffle_seed = shuffle_seed
        self.random_state = random_state
        self.scoring = scoring
        self.checkpoint = checkpoint
        self.cell_timeout = cell_timeout
        self.cell_retries = cell_retries
        self.elastic = elastic
        self.batched_rungs = batched_rungs
        self.patience = patience
        self.tol = tol

    def _brackets(self) -> list:
        if self.n_initial_parameters == "grid":
            n0 = len(list(ParameterGrid(self.parameters)))
        else:
            n0 = int(self.n_initial_parameters)
        return [(0, n0, int(self.n_initial_epochs))]


class HyperbandSearchCV(BaseIncrementalSearchCV):
    """Hyperband: every :func:`hyperband_brackets` bracket of
    :class:`SuccessiveHalvingSearchCV`, from most exploratory (many
    candidates, one epoch) to least (few candidates, ``max_epochs``
    each), sharing the data plane, the journal, and — per bracket —
    one batched program. ``cv_results_`` spans all brackets
    (``bracket_`` column); ``best_*`` is the argmax over every
    candidate's final score, mirroring dask-ml's
    ``HyperbandSearchCV`` metadata shape."""

    def __init__(self, estimator, parameters, *, max_epochs=27,
                 aggressiveness=3, test_size=0.2, n_blocks=4,
                 shuffle_seed=0, random_state=0, scoring=None,
                 checkpoint=None, cell_timeout=None, cell_retries=0,
                 elastic=None, batched_rungs=True, patience=None,
                 tol=1e-3):
        self.estimator = estimator
        self.parameters = parameters
        self.max_epochs = max_epochs
        self.aggressiveness = aggressiveness
        self.test_size = test_size
        self.n_blocks = n_blocks
        self.shuffle_seed = shuffle_seed
        self.random_state = random_state
        self.scoring = scoring
        self.checkpoint = checkpoint
        self.cell_timeout = cell_timeout
        self.cell_retries = cell_retries
        self.elastic = elastic
        self.batched_rungs = batched_rungs
        self.patience = patience
        self.tol = tol

    def _brackets(self) -> list:
        return hyperband_brackets(int(self.max_epochs),
                                  int(self.aggressiveness))
