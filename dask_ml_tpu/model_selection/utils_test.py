"""Test doubles for the search driver
(reference: model_selection/utils_test.py).

These are behavioral probes, not models: ``FailingClassifier`` drives the
``error_score``/FIT_FAILURE tests (reference: utils_test.py:76-93),
``MockClassifier`` is a minimal duck-typed estimator, ``ScalingTransformer``
a trivial pipeline stage, ``CheckXClassifier`` asserts what data actually
reaches ``fit``, and ``CountingTransformer`` counts real (non-memoized) fit
executions so work-sharing/CSE is directly testable.
"""

from __future__ import annotations

import threading

import numpy as np
from sklearn.base import BaseEstimator, ClassifierMixin, TransformerMixin


class MockClassifier(BaseEstimator, ClassifierMixin):
    """Trivial classifier recording what it saw
    (reference: utils_test.py:12-45)."""

    def __init__(self, foo_param=0):
        self.foo_param = foo_param

    def fit(self, X, y=None):
        self.classes_ = np.unique(np.asarray(y)) if y is not None else None
        self.n_features_in_ = np.asarray(X).shape[1]
        return self

    def predict(self, X):
        return np.zeros(np.asarray(X).shape[0], dtype=np.int64)

    def score(self, X=None, y=None):
        return 1.0 if self.foo_param > 1 else 0.0


class ScalingTransformer(BaseEstimator, TransformerMixin):
    """Multiply by a factor (reference: utils_test.py:48-56)."""

    def __init__(self, factor=1.0):
        self.factor = factor

    def fit(self, X, y=None):
        self.factor_ = self.factor
        return self

    def transform(self, X):
        return np.asarray(X) * self.factor_


class CountingTransformer(ScalingTransformer):
    """ScalingTransformer that counts actual fit executions across threads —
    the probe for prefix-sharing (one fit per distinct config, not per
    candidate)."""

    _lock = threading.Lock()
    n_fits = 0  # class-level: survives the driver's deepcopies

    def fit(self, X, y=None):
        with CountingTransformer._lock:
            CountingTransformer.n_fits += 1
        return super().fit(X, y)

    @classmethod
    def reset(cls):
        with cls._lock:
            cls.n_fits = 0


class FailingClassifier(BaseEstimator, ClassifierMixin):
    """Raises inside fit when parameter == FAILING_PARAMETER
    (reference: utils_test.py:76-93)."""

    FAILING_PARAMETER = 2

    def __init__(self, parameter=None):
        self.parameter = parameter

    def fit(self, X, y=None):
        if self.parameter == FailingClassifier.FAILING_PARAMETER:
            raise ValueError("Failing classifier failed as required")
        self.classes_ = np.unique(np.asarray(y)) if y is not None else None
        return self

    def predict(self, X):
        return np.zeros(np.asarray(X).shape[0], dtype=np.int64)

    def score(self, X=None, y=None):
        return 0.0


class FailingTransformer(BaseEstimator, TransformerMixin):
    """Transformer that raises inside fit when parameter ==
    FAILING_PARAMETER — drives FIT_FAILURE propagation through FeatureUnion
    expansion (reference: test_model_selection.py:466-537 uses
    FailingClassifier inside composite grids the same way)."""

    FAILING_PARAMETER = 2

    def __init__(self, parameter=None):
        self.parameter = parameter

    def fit(self, X, y=None):
        if self.parameter == FailingTransformer.FAILING_PARAMETER:
            raise ValueError("Failing transformer failed as required")
        return self

    def transform(self, X):
        return np.asarray(X)


class CheckXClassifier(BaseEstimator, ClassifierMixin):
    """Asserts the X it receives equals ``expected_X``
    (reference: utils_test.py:59-73)."""

    def __init__(self, expected_X=None):
        self.expected_X = expected_X

    def fit(self, X, y=None):
        assert np.array_equal(np.asarray(X), np.asarray(self.expected_X))
        self.classes_ = np.unique(np.asarray(y))
        return self

    def predict(self, X):
        return np.zeros(np.asarray(X).shape[0], dtype=np.int64)

    def score(self, X=None, y=None):
        return 1.0


class CheckingClassifier(BaseEstimator, ClassifierMixin):
    """Probe classifier asserting properties of X/y/fit_params at fit and
    predict time — for testing that pipelines, CV, and meta-estimators do
    not alter their inputs (reference: utils_test.py:137-175; the test
    contract, not the implementation, is what is mirrored)."""

    def __init__(self, check_y=None, check_X=None, foo_param=0,
                 expected_fit_params=None):
        self.check_y = check_y
        self.check_X = check_X
        self.foo_param = foo_param
        self.expected_fit_params = expected_fit_params

    def fit(self, X, y, **fit_params):
        assert len(X) == len(y)
        if self.check_X is not None:
            assert self.check_X(X)
        if self.check_y is not None:
            assert self.check_y(y)
        self.classes_ = np.unique(np.asarray(y))
        if self.expected_fit_params:
            missing = set(self.expected_fit_params) - set(fit_params)
            assert not missing, (
                f"Expected fit parameter(s) {sorted(missing)} not seen."
            )
            for key, value in fit_params.items():
                assert len(value) == len(X), (
                    f"Fit parameter {key} has length {len(value)}; "
                    f"expected {len(X)}."
                )
        return self

    def predict(self, X):
        if self.check_X is not None:
            assert self.check_X(X)
        return self.classes_[np.zeros(len(np.asarray(X)), dtype=np.int64)]

    def score(self, X=None, y=None):
        # the reference scores foo_param > 1 as 1. vs 0. via its mock
        # convention; keep that shape so grid tests can rank on foo_param
        return 1.0 if self.foo_param > 1 else 0.0
