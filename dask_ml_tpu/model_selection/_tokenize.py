"""Content-addressed tokens for work-sharing in the search driver.

The reference registers ``dask.base.normalize_token`` rules for estimators and
CV splitters so that graph keys are content-addressed and identical
(estimator-config, data) fits collapse to one task
(reference: model_selection/_normalize.py:17-62, used by the ``seen`` maps in
_search.py:281-345). Our driver's memoization needs the same property but only
*within one search call*, so data identity can be a (split-id, role) pair and
only estimator configurations need content hashing.
"""

from __future__ import annotations

import hashlib

import numpy as np


def _update(h, s: str):
    h.update(s.encode())


# ---------------------------------------------------------------------------
# callable/value content identity
#
# Tokens must change when a callable's BEHAVIOR changes and be stable across
# processes. Neither module+qualname (every lambda is "<lambda>"; editing a
# function body changes nothing) nor pickle (serializes module-level
# functions by reference) nor repr (embeds addresses) has both properties —
# so callables are identified by bytecode + referenced global names +
# constants + closure/default/instance values, recursively.
# ---------------------------------------------------------------------------


_ADDR_RE = None


def _stable_repr(obj) -> str:
    """``repr`` with memory addresses stripped, so identities are stable
    across processes (default object reprs embed ``at 0x7f...``)."""
    global _ADDR_RE
    if _ADDR_RE is None:
        import re

        _ADDR_RE = re.compile(r"0x[0-9a-fA-F]+")
    return _ADDR_RE.sub("0x", repr(obj))


def _code_identity(code):
    """Identity of a code object: bytecode + referenced GLOBAL NAMES +
    constants (nested code objects — inner lambdas/defs — recurse instead of
    repr'ing, which would embed an address). co_names matters: two lambdas
    calling different globals have byte-identical co_code."""
    consts = tuple(
        _code_identity(c) if hasattr(c, "co_code") else _stable_repr(c)
        for c in code.co_consts
    )
    return ("co", code.co_code, code.co_names, consts)


def _value_identity(obj, seen=None):
    """Process-stable content identity of an arbitrary captured value."""
    if callable(obj):
        return _callable_identity(obj, seen)
    if hasattr(obj, "shape") and hasattr(obj, "dtype") and hasattr(
            obj, "__array__"):
        # ndarray-likes incl. jax Arrays: repr() truncates ('...') and would
        # collide distinct contents (same rule as _normalize below)
        arr = np.ascontiguousarray(np.asarray(obj))
        if arr.dtype == object:
            return ("nd-obj", arr.shape, _stable_repr(arr.tolist()))
        return ("nd", arr.shape, str(arr.dtype), arr.tobytes())
    if isinstance(obj, (list, tuple, dict, set, frozenset)):
        # containers join the cycle guard: self-referential lists/dicts are
        # legal Python and must not blow the stack
        seen = set() if seen is None else seen
        if id(obj) in seen:
            return ("cycle",)
        seen = seen | {id(obj)}
        if isinstance(obj, (list, tuple)):
            return ("seq", type(obj).__name__,
                    tuple(_value_identity(v, seen) for v in obj))
        if isinstance(obj, dict):
            return ("map", tuple(
                (_stable_repr(k), _value_identity(obj[k], seen))
                for k in sorted(obj, key=repr)))
        return ("set", tuple(sorted(
            (_value_identity(v, seen) for v in obj), key=repr)))
    return _stable_repr(obj)


def _object_identity(obj, seen=None):
    """Identity of an object by class + attribute CONTENT (function-valued
    attrs by their code), for scorer instances and bound-method selves."""
    seen = set() if seen is None else seen
    if id(obj) in seen:
        return ("cycle",)  # self-referential object graph: mark and stop
    seen = seen | {id(obj)}
    attrs = getattr(obj, "__dict__", None)
    if isinstance(attrs, dict):
        attr_id = tuple(
            (k, _value_identity(v, seen)) for k, v in sorted(attrs.items())
        )
    else:
        # __slots__-backed objects have no __dict__; their state lives in
        # the slot descriptors declared across the MRO
        slot_names = sorted({
            name
            for klass in type(obj).__mro__
            for name in getattr(klass, "__slots__", ())
        })
        if slot_names:
            attr_id = tuple(
                (name, _value_identity(getattr(obj, name, "<unset>"), seen))
                for name in slot_names
            )
        else:
            attr_id = _stable_repr(obj)
    return ("obj", type(obj).__module__, type(obj).__qualname__, attr_id)


def _cell_value(cell):
    try:
        return cell.cell_contents
    except ValueError:  # unbound cell ("Cell is empty")
        return "<empty-cell>"


def _callable_identity(fn, seen=None):
    import functools

    outer_seen = set() if seen is None else seen
    if id(fn) in outer_seen:
        return ("cycle",)
    seen = outer_seen | {id(fn)}
    if isinstance(fn, functools.partial):
        # partial's __dict__ is empty — func/args/keywords carry the state
        return ("partial", _callable_identity(fn.func, seen),
                tuple(_value_identity(a, seen) for a in fn.args),
                tuple((k, _value_identity(v, seen))
                      for k, v in sorted(fn.keywords.items())))
    code = getattr(fn, "__code__", None)
    if code is not None:
        # a plain function/lambda/method: identify by its CODE, not by
        # pickle — pickle serializes module-level functions by reference
        # (module+qualname), so editing the body would not invalidate
        cells = tuple(
            _value_identity(_cell_value(c), seen)
            for c in (getattr(fn, "__closure__", None) or ())
        )
        defaults = tuple(
            _value_identity(v, seen)
            for v in (getattr(fn, "__defaults__", None) or ())
        )
        kwdefaults = tuple(
            (k, _value_identity(v, seen))
            for k, v in sorted((getattr(fn, "__kwdefaults__", None)
                                or {}).items())
        )
        # a bound method's behavior also depends on its instance's state
        self_obj = getattr(fn, "__self__", None)
        self_id = (None if self_obj is None
                   else _object_identity(self_obj, seen))
        return ("fn", getattr(fn, "__module__", ""),
                getattr(fn, "__qualname__", ""), _code_identity(code),
                cells, defaults, kwdefaults, self_id)
    # non-function callable (e.g. a make_scorer product): class + attribute
    # values, with function-valued attrs (the score_func) by code identity.
    # Delegate with the OUTER seen — _object_identity does its own
    # check-and-add for fn, and the id we just added would read as a cycle.
    return _object_identity(fn, outer_seen)


def _normalize(obj, h):
    """Feed a stable representation of ``obj`` into hash ``h``.

    Estimators normalize to (qualified class name, sorted shallow params) with
    nested estimators/arrays recursed — the same rule as the reference's
    ``normalize_estimator`` (reference: _normalize.py:17-23).
    """
    if isinstance(obj, type):
        _update(h, f"type:{obj.__module__}.{obj.__qualname__}")
    elif hasattr(obj, "get_params") and hasattr(obj, "set_params"):
        _update(h, f"est:{type(obj).__module__}.{type(obj).__qualname__}(")
        for k, v in sorted(obj.get_params(deep=False).items()):
            _update(h, f"{k}=")
            _normalize(v, h)
            _update(h, ",")
        _update(h, ")")
    elif isinstance(obj, np.ndarray) or (
        hasattr(obj, "shape") and hasattr(obj, "dtype")
        and hasattr(obj, "__array__")
    ):
        # covers jax Arrays and other ndarray-likes too: repr() would
        # truncate large arrays ('...') and collide distinct contents
        arr = np.ascontiguousarray(np.asarray(obj))
        _update(h, f"nd:{arr.shape}:{arr.dtype}:")
        if arr.dtype == object:
            _update(h, repr(arr.tolist()))
        else:
            h.update(arr.tobytes())
    elif isinstance(obj, (list, tuple)):
        _update(h, f"{type(obj).__name__}[")
        for v in obj:
            _normalize(v, h)
            _update(h, ",")
        _update(h, "]")
    elif isinstance(obj, dict):
        _update(h, "dict{")
        for k in sorted(obj, key=repr):
            _update(h, f"{k!r}:")
            _normalize(obj[k], h)
            _update(h, ",")
        _update(h, "}")
    elif callable(obj):
        # content identity, not module+qualname: two lambdas (or two edits
        # of the same function) as hyperparameter values must NOT collide —
        # a name-keyed token would share one memoized fit between candidates
        # with different callables
        _normalize(_callable_identity(obj), h)
    else:
        _update(h, f"{type(obj).__name__}:{obj!r}")


def tokenize(*args) -> str:
    h = hashlib.sha256()
    for a in args:
        _normalize(a, h)
        _update(h, ";")
    return h.hexdigest()[:32]
