"""Content-addressed tokens for work-sharing in the search driver.

The reference registers ``dask.base.normalize_token`` rules for estimators and
CV splitters so that graph keys are content-addressed and identical
(estimator-config, data) fits collapse to one task
(reference: model_selection/_normalize.py:17-62, used by the ``seen`` maps in
_search.py:281-345). Our driver's memoization needs the same property but only
*within one search call*, so data identity can be a (split-id, role) pair and
only estimator configurations need content hashing.
"""

from __future__ import annotations

import hashlib

import numpy as np


def _update(h, s: str):
    h.update(s.encode())


def _normalize(obj, h):
    """Feed a stable representation of ``obj`` into hash ``h``.

    Estimators normalize to (qualified class name, sorted shallow params) with
    nested estimators/arrays recursed — the same rule as the reference's
    ``normalize_estimator`` (reference: _normalize.py:17-23).
    """
    if isinstance(obj, type):
        _update(h, f"type:{obj.__module__}.{obj.__qualname__}")
    elif hasattr(obj, "get_params") and hasattr(obj, "set_params"):
        _update(h, f"est:{type(obj).__module__}.{type(obj).__qualname__}(")
        for k, v in sorted(obj.get_params(deep=False).items()):
            _update(h, f"{k}=")
            _normalize(v, h)
            _update(h, ",")
        _update(h, ")")
    elif isinstance(obj, np.ndarray) or (
        hasattr(obj, "shape") and hasattr(obj, "dtype")
        and hasattr(obj, "__array__")
    ):
        # covers jax Arrays and other ndarray-likes too: repr() would
        # truncate large arrays ('...') and collide distinct contents
        arr = np.ascontiguousarray(np.asarray(obj))
        _update(h, f"nd:{arr.shape}:{arr.dtype}:")
        if arr.dtype == object:
            _update(h, repr(arr.tolist()))
        else:
            h.update(arr.tobytes())
    elif isinstance(obj, (list, tuple)):
        _update(h, f"{type(obj).__name__}[")
        for v in obj:
            _normalize(v, h)
            _update(h, ",")
        _update(h, "]")
    elif isinstance(obj, dict):
        _update(h, "dict{")
        for k in sorted(obj, key=repr):
            _update(h, f"{k!r}:")
            _normalize(obj[k], h)
            _update(h, ",")
        _update(h, "}")
    elif callable(obj):
        _update(h, f"fn:{getattr(obj, '__module__', '')}."
                   f"{getattr(obj, '__qualname__', repr(obj))}")
    else:
        _update(h, f"{type(obj).__name__}:{obj!r}")


def tokenize(*args) -> str:
    h = hashlib.sha256()
    for a in args:
        _normalize(a, h)
        _update(h, ";")
    return h.hexdigest()[:32]
