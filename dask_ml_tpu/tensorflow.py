"""TensorFlow hand-off (reference: tensorflow.py:1-5 re-exports
``dask-tensorflow``'s cluster bootstrap).

The reference spins a TF cluster on dask workers. Here the hand-off is data
export: host arrays feed ``tf.data`` directly, and fitted-model state
transfers as plain ndarrays::

    from dask_ml_tpu.tensorflow import to_numpy, export_learned_attrs
    ds = tf.data.Dataset.from_tensor_slices((to_numpy(Xd), to_numpy(yd)))
    weights = export_learned_attrs(fitted_estimator)
"""

from dask_ml_tpu.interop import export_learned_attrs, to_numpy  # noqa: F401
